"""Benchmark entry: prints ONE JSON line for the driver.

Primary metric (BASELINE.md tracked metric #2): MNIST training
steps/sec on the XLA device (TPU when present), ``vs_baseline`` =
speedup over the reference-style numpy backend on the same host
(BASELINE.json: "samples/MNIST: 2-layer All2All softmax (numpy_run CPU
baseline)").

``extra`` carries the other BASELINE.md tracked metrics measured the
same run: CIFAR-10 conv-stack images/sec on the XLA device (metric #1's
conv-scale stand-in until the ImageNet tier has data), AlexNet-shaped
synthetic images/sec when that model is available, and the DP
gradient-sync bytes/step (metric #3).

Measurement method: the XLA path dispatches CHUNKS of whole epochs as
one XLA program (see ``XLAStep._dispatch_epoch``); timing starts after
the first chunk (covers compilation), each subsequent chunk is timed
individually (its metric fetch is the synchronization point — the
remote tunnel's block_until_ready does not block, BASELINE.md round
3), and BOTH the best and the median chunk rate are reported.

Key convention (since round 4, ADVICE r3): every PRIMARY key — the
headline ``value`` and ``extra`` keys like ``lm_57M_tokens_per_sec`` —
carries the MEDIAN chunk rate, the figure comparable with rounds 1-2's
average-rate timing; the fastest chunk (the stable device-side figure
under the tunnel's multi-second dispatch jitter) is recorded under the
explicit ``*_best`` suffix. Round 3 alone put best under the primary
keys — compare r3 primary keys against r4's ``*_best``, not r4's
primaries. Every timed chunk carries its full share of dispatch +
metric-fetch cost; nothing is served from pre-computed results.

Work counts come from the telemetry registry (ISSUE 3): every row's
numerator is a delta of the SAME ``veles_loader_*_total`` counters the
runtime increments per served minibatch (``_train_counter``), so bench
figures and a /metrics scrape of the same run can never disagree.
"""

import argparse
import glob
import json
import os
import re
import sys
import time

#: v5e bf16 peak (dense MXU) used for every MFU figure
PEAK_BF16_FLOPS = 197e12


def device_matmul_tflops(n=8192, reps_lo=16, reps_hi=80):
    """Calibration row (VERDICT r4 #5): a fixed DEVICE-ONLY bf16
    matmul rate, so cross-round bench tables can flag tunnel slow
    phases (the same build measured MNIST 17.5k and 9.0k steps/s
    hours apart — BASELINE.md round 4).

    Method: chained n³ matmuls under one ``lax.scan`` dispatch — each
    result feeds the next (independent identical dispatches get CSE'd
    into one execution; BASELINE.md round-4 microbench pitfall) — with
    a scalar readback as the sync point (``block_until_ready`` does
    not block through the tunnel). The rate comes from the DIFFERENCE
    between a ``reps_hi`` and a ``reps_lo`` run, which cancels the
    ~100ms tunnel round-trip and any constant dispatch overhead."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    import numpy

    gen = numpy.random.Generator(numpy.random.PCG64(7))
    a = jnp.asarray(gen.standard_normal((n, n), numpy.float32),
                    jnp.bfloat16)
    b = jnp.asarray(gen.standard_normal((n, n), numpy.float32)
                    / numpy.sqrt(n), jnp.bfloat16)

    def chain(reps, samples=3):
        @jax.jit
        def run(a, b):
            def step(c, _):
                return jnp.matmul(
                    c, b, preferred_element_type=jnp.bfloat16), ()
            c, _ = lax.scan(step, a, None, length=reps)
            return c.astype(jnp.float32).sum()
        float(run(a, b))                   # compile + warm
        best = float("inf")
        for _ in range(samples):           # min-of-N: the tunnel adds
            t0 = time.perf_counter()       # multi-second jitter spikes
            float(run(a, b))               # scalar readback = sync
            best = min(best, time.perf_counter() - t0)
        return best

    dt = chain(reps_hi) - chain(reps_lo)
    if dt <= 0:
        raise RuntimeError(
            "calibration difference non-positive (%.3fs) — tunnel "
            "jitter swamped the measurement" % dt)
    flops = 2.0 * n ** 3 * (reps_hi - reps_lo)
    return flops / dt / 1e12


def lm_train_flops_per_token(dim, layers, ffn_hidden, vocab, seq):
    """Attention-AWARE train FLOPs per token (VERDICT r4 #2 — the
    6·N-only form under-counts long-context rows where attention
    FLOPs rival the matmul params'):

    * matmul parameters: 6 FLOPs each (2 fwd + 4 bwd) over qkv/out/
      ffn/vocab-head weights. The EMBEDDING table is excluded — the
      lookup is a gather, not a matmul (this makes the figures here
      slightly stricter than round 4's 6·N_total arithmetic, which
      credited the 12.6M-param embedding as compute);
    * attention score/context matmuls, CAUSAL coverage: per layer per
      sequence 6·S(S+1)·dim FLOPs (2 fwd + 4 bwd matmuls over the
      S(S+1)/2 causal pairs) -> 6·(S+1)·dim per token per layer.
      Causal, not the 12·L·S·d full-square form: MFU counts the
      FLOPs a perfect implementation NEEDS. The Pallas kernels (auto
      at S>=1024) really do skip the masked half via their fori_loop
      bounds; the scan-flash path at shorter S computes the full
      square and masks (a cond skip measured slower there —
      parallel/flash.py), which simply reads as lower MFU here."""
    n_mm = layers * (4 * dim * dim + 2 * dim * ffn_hidden) \
        + dim * vocab
    return 6.0 * n_mm + 6.0 * layers * (seq + 1) * dim


#: the at-scale LM rows: ONE place for each row's loader/model config
#: — the throughput function AND its MFU accounting both read these,
#: so a retune cannot desynchronize the two
LM_ROWS = {
    "57M": (
        {"minibatch_size": 8, "n_train": 512, "n_valid": 32,
         "seq_len": 512, "vocab": 32, "max_period": 8},
        {"dim": 768, "heads": 12, "layers": 8, "ffn_hidden": 3072,
         "attn_block": 256}),
    "57M_s8k": (
        # B=8 from the round-5 sweep (104.7k vs 103.4k at B=4, 88k at
        # the round-4 B=2; the fused backward freed the memory room)
        {"minibatch_size": 8, "n_train": 64, "n_valid": 8,
         "seq_len": 8192, "vocab": 32, "max_period": 8},
        {"dim": 768, "heads": 12, "layers": 8, "ffn_hidden": 3072,
         "attn_block": 256}),
    "110M": (
        {"minibatch_size": 8, "n_train": 512, "n_valid": 32,
         "seq_len": 512, "vocab": 16384, "max_period": 8},
        {"dim": 768, "heads": 12, "layers": 12, "ffn_hidden": 3072,
         "attn_block": 256}),
    "110M_s8k": (
        # B=4 from the round-5 sweep (66.8k = 35.2% MFU vs 62.4k at
        # the round-4 B=2; B=8 exceeds HBM — 17.5G vs 15.75G)
        {"minibatch_size": 4, "n_train": 32, "n_valid": 4,
         "seq_len": 8192, "vocab": 16384, "max_period": 8},
        {"dim": 768, "heads": 12, "layers": 12, "ffn_hidden": 3072,
         "attn_block": 256}),
    "345M": (
        {"minibatch_size": 8, "n_train": 256, "n_valid": 16,
         "seq_len": 512, "vocab": 16384, "max_period": 8},
        {"dim": 1024, "heads": 16, "layers": 24, "ffn_hidden": 4096,
         "attn_block": 256}),
}


def _row_flops_per_token(row):
    ld, md = LM_ROWS[row]
    return lm_train_flops_per_token(
        md["dim"], md["layers"], md["ffn_hidden"], ld["vocab"],
        ld["seq_len"])


def _mfu(extra, key, mfu_key, row):
    """Derive an MFU figure from a recorded median tokens/sec row."""
    if key in extra:
        extra[mfu_key] = round(
            extra[key] * _row_flops_per_token(row)
            / PEAK_BF16_FLOPS, 4)


def _build_mnist(backend, name, mb=100, n_train=6000, n_valid=1000,
                 max_epochs=None):
    import veles.prng as prng
    prng.seed_all(99)
    from veles.config import root
    from veles.znicz_tpu.models import mnist
    root.mnist.loader.minibatch_size = mb
    root.mnist.loader.n_train = n_train
    root.mnist.loader.n_valid = n_valid
    if max_epochs is not None:
        root.mnist.decision.max_epochs = max_epochs
        # patience must exceed the dispatch chunk (see _xla_throughput)
        root.mnist.decision.fail_iterations = 100000
    wf = mnist.create_workflow(name=name)
    wf.initialize(device=backend)
    return wf


def _train_counter(loader, kind="minibatches", scale=1.0):
    """A cumulative work-count reader over the telemetry registry
    (ISSUE 3): bench rows and runtime metrics read the SAME
    ``veles_loader_*_total{cls="train"}`` counters the loader
    increments per served minibatch, so the two can never disagree.
    ``kind``: 'minibatches' (steps) or 'samples' (images; × seq =
    tokens via ``scale``)."""
    from veles import telemetry
    name = "veles_loader_%s_total" % kind

    def read():
        return telemetry.get_registry().counter_total(
            name, loader=loader.name, cls="train") * scale
    return read


def _mnist_numpy_stepper(name="BenchNumpy"):
    """(one_step, steps_done) for a freshly built numpy MNIST
    workflow — shared by the baseline row and the profiler-overhead
    row so both price the same training loop."""
    from veles.loader.base import CLASS_TRAIN
    wf = _build_mnist("numpy", name)
    loader = wf.loader
    steps_done = _train_counter(loader)

    def one_step():
        loader.run()
        while loader.minibatch_class != CLASS_TRAIN:
            loader.run()
        for u in wf.forwards:
            u.run()
        wf.evaluator.run()
        for gd in reversed(wf.gds):
            gd.run()

    return one_step, steps_done


def numpy_steps_per_sec(n_steps=30):
    one_step, steps_done = _mnist_numpy_stepper()
    one_step()  # warm caches
    c0 = steps_done()
    t0 = time.perf_counter()
    for _ in range(n_steps):
        one_step()
    return (steps_done() - c0) / (time.perf_counter() - t0)


def profiler_overhead_pct(n_steps=60):
    """ISSUE 10 satellite: percent slowdown of the numpy MNIST train
    loop while the sampling profiler runs at its default rate
    (veles/profiling.py; the acceptance bound is < 3%%). Measured
    off-on-off so ambient host drift cancels: overhead = 1 -
    rate(on) / mean(rate(off_before), rate(off_after)), floored at 0
    (noise can make the profiled run the faster one)."""
    from veles.profiling import SamplingProfiler
    one_step, _ = _mnist_numpy_stepper("BenchProfOverhead")
    one_step()  # warm caches

    def rate():
        t0 = time.perf_counter()
        for _ in range(n_steps):
            one_step()
        return n_steps / (time.perf_counter() - t0)

    r_before = rate()
    profiler = SamplingProfiler()
    profiler.start()
    try:
        r_on = rate()
    finally:
        profiler.stop()
    r_off = (r_before + rate()) / 2.0
    return max((1.0 - r_on / r_off) * 100.0, 0.0)


def _profiler_row(extra):
    """Record the profiler-overhead bench guarded (device-independent
    row: it runs, and means the same thing, with or without a TPU).
    Directionality: the key says 'overhead', so the self-check flags
    it when it goes UP."""
    try:
        extra["profiler_overhead_pct"] = round(
            profiler_overhead_pct(), 2)
    except Exception as exc:
        extra["profiler_overhead_pct_error"] = str(exc)[:200]


def model_stats_overhead_pct(measure_chunks=2):
    """ISSUE 15 satellite: percent step-time cost of the in-graph
    model-health stats (per-GD-unit grad/weight/update norms +
    non-finite counts fused into the compiled step —
    veles/model_health.py). Measured off-on-off on the SAME XLA MNIST
    chunk loop the throughput row uses, so ambient drift cancels:
    overhead = 1 - rate(on) / mean(rate(off_before), rate(off_after)),
    floored at 0. Each toggle re-keys the compiled program
    (collect_stats is part of the compile-cache key) and
    _timed_chunks' warmup chunk absorbs the rebuild before timing.
    Acceptance: < 2%."""
    wf = _build_mnist("xla", "BenchStatsOverhead", max_epochs=4096)
    loader, step = wf.loader, wf.xla_step
    step.epochs_per_dispatch = 16
    counter = _train_counter(loader)

    def rate(enabled):
        step.set_stats_enabled(enabled)
        best, _median = _timed_chunks(loader, step, counter,
                                      measure_chunks)
        return best

    r_off1 = rate(False)
    r_on = rate(True)
    r_off2 = rate(False)
    r_off = (r_off1 + r_off2) / 2.0
    return max((1.0 - r_on / r_off) * 100.0, 0.0)


def _model_stats_row(extra):
    """Record the model-stats-overhead bench guarded (runs on any jax
    backend). Key says 'overhead' -> the self-check flags UP moves."""
    try:
        extra["model_stats_overhead_pct"] = round(
            model_stats_overhead_pct(), 2)
    except Exception as exc:
        extra["model_stats_overhead_pct_error"] = str(exc)[:200]


def _run_one_chunk(loader, step):
    """Serve exactly one dispatch chunk (the serve that crosses into an
    undispatched epoch triggers the next chunk). The ONE place that
    reads XLAStep's chunk bookkeeping."""
    while True:
        loader.run()
        step.run()
        if bool(loader.epoch_ended) and \
                loader.epoch_number + 1 >= \
                step._chunk_epoch0 + step._chunk_len:
            return


def _timed_chunks(loader, step, counter, measure_chunks):
    """(best_rate, median_rate) over ``measure_chunks`` individually
    timed chunks, after one warmup chunk that covers compilation.
    ``counter()`` is a cumulative registry reader (_train_counter);
    each chunk's rate is its counter delta over its wall time.
    Per-chunk timing (not a sum): the remote tunnel adds multi-second
    jitter to individual dispatches, and the chunk's metric fetch
    blocks on device completion, so the fastest chunk is the stable
    device-side figure while the median keeps the reporting honest
    (same convention as bench_alexnet; the fetch inside
    _run_one_chunk is the synchronization point — block_until_ready
    alone does not block through the tunnel, BASELINE.md round 3)."""
    _run_one_chunk(loader, step)
    rates = []
    for _ in range(measure_chunks):
        c0 = counter()
        t0 = time.perf_counter()
        _run_one_chunk(loader, step)
        rates.append((counter() - c0)
                     / (time.perf_counter() - t0))
    rates.sort()
    return rates[-1], rates[len(rates) // 2]


def xla_mnist_bench(measure_chunks=2):
    """MNIST steps/s on the XLA path, chunk-aligned timing.

    The chunk size is pinned to the adaptive mode's steady state for
    this workload (auto mode ramps 1 → 64 over a few dispatches; the
    pin just skips timing the ramp)."""
    wf = _build_mnist("xla", "BenchXLA", max_epochs=1024)
    loader, step = wf.loader, wf.xla_step
    step.epochs_per_dispatch = 64
    best, median = _timed_chunks(
        loader, step, _train_counter(loader), measure_chunks)
    return best, median, _grad_sync_bytes(step)


def _grad_sync_bytes(step):
    """BASELINE.md metric #3: bytes of gradient all-reduced per step
    under DP (equals the trainable-param payload the reference's
    master/slave link shipped per update)."""
    from veles.znicz_tpu import parallel
    import jax
    host = jax.tree_util.tree_map(lambda a: __import__("numpy").asarray(a),
                                  step.params)
    return parallel.grad_sync_bytes(host)


def _wire_tx_bytes():
    """tx-side frame bytes from the SAME ``veles_wire_bytes_total``
    counters the runtime increments — excluding slave-labelled
    absorbed copies (co-located master+slave share one registry and
    the slave pushes its counter state to the master; counting those
    too would double every frame)."""
    from veles import telemetry
    state = telemetry.get_registry().counter_state(
        exclude_label_keys=("slave",))
    return sum(v for (name, items), v in state.items()
               if name == "veles_wire_bytes_total"
               and ("direction", "tx") in items)


def _slave_jobs_total():
    """Cumulative ``veles_slave_jobs_done_total`` from the registry,
    EXCLUDING slave-labelled absorbed copies (co-located master+slave
    share one registry and the master re-absorbs each slave's pushed
    state under a ``slave="<id>"`` label — counting those too would
    double every job)."""
    from veles import telemetry
    state = telemetry.get_registry().counter_state(
        exclude_label_keys=("slave",))
    return sum(v for (name, items), v in state.items()
               if name == "veles_slave_jobs_done_total")


def _dist_wire_row(codec, n_slaves=1, max_epochs=2):
    """One co-located master + ``n_slaves`` run over real sockets on
    the numpy backend (the row measures the WIRE protocol, not
    compute — it runs, and means the same thing, with or without a
    TPU); -> (wire bytes per served job, jobs per second). Both
    numerators come from the SAME registry counters the runtime
    increments (``veles_wire_bytes_total`` /
    ``veles_slave_jobs_done_total``), so the row and a /metrics
    scrape of the run can never disagree."""
    import threading
    from veles.client import SlaveClient
    from veles.server import MasterServer
    master = _build_mnist("numpy", "BenchWireM%d%s" % (n_slaves, codec),
                          mb=50, n_train=500, n_valid=100,
                          max_epochs=max_epochs)
    server = MasterServer(master, "127.0.0.1:0",
                          max_epochs=max_epochs, grad_codec=codec)
    server.start_background()
    try:
        # guarded from the very first statement after the server is
        # live: a slave-workflow build that raises here used to leak
        # the master's serving thread, listener and workflow for the
        # rest of the bench process (zlint resource-leak)
        address = "127.0.0.1:%d" % server.bound_address[1]
        slaves = []
        for i in range(n_slaves):
            wf = _build_mnist("numpy", "BenchWireS%d%s-%d"
                              % (n_slaves, codec, i), mb=50,
                              n_train=500, n_valid=100,
                              max_epochs=max_epochs)
            wf.is_slave = True
            slaves.append(wf)
        ok = [0] * n_slaves
        errors = []

        def pump(i):
            try:
                ok[i] = SlaveClient(
                    slaves[i], address,
                    name="bench-%s-%d" % (codec, i),
                    grad_codec=codec).run_forever()
            except Exception as exc:   # surfaced below: a dead-slave
                errors.append(exc)     # row must be an _error entry,
                                       # never a bogus data point

        before = _wire_tx_bytes()
        jobs_before = _slave_jobs_total()
        threads = [threading.Thread(target=pump, args=(i,))
                   for i in range(n_slaves)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        server.request_stop()
    wall = time.perf_counter() - t0
    moved = _wire_tx_bytes() - before
    total_jobs = _slave_jobs_total() - jobs_before
    if errors:
        raise RuntimeError("slave failed: %s" % errors[0])
    if not total_jobs or not sum(ok):
        raise RuntimeError("no jobs completed — nothing to measure")
    if server.faults["codec_fallbacks"]:
        raise RuntimeError("codec %r fell back to 'none' — the row "
                           "would measure the wrong thing" % codec)
    return moved / total_jobs, total_jobs / wall


def _grad_codec_rows(extra):
    """The 318,040-byte plateau as a tracked, falsifiable trajectory:
    measured wire bytes per sync step for EVERY codec, plus a 2-slave
    distributed throughput row (protocol-level steps/s, none vs int8
    — co-located numpy processes, so this prices the wire+codec path,
    not device scaling)."""
    for codec in ("none", "bf16", "int8", "topk"):
        key = "grad_sync_wire_bytes_per_step_%s" % codec
        try:
            bytes_per_job, _ = _dist_wire_row(codec, n_slaves=1)
            extra[key] = int(round(bytes_per_job))
        except Exception as exc:
            extra[key + "_error"] = str(exc)[:200]
    for codec in ("none", "int8"):
        key = "dist_2slave_steps_per_sec_%s" % codec
        try:
            _, steps_per_sec = _dist_wire_row(codec, n_slaves=2)
            extra[key] = round(steps_per_sec, 1)
        except Exception as exc:
            extra[key + "_error"] = str(exc)[:200]


def _dist_scaling_rows(extra, codec="int8"):
    """ROADMAP item 3's missing half-row: protocol-level scaling
    efficiency at N=1/2/4/8 co-located slaves over the reactor wire
    plane under the shipped ``int8`` codec —
    ``dist_scaling_steps_per_sec_nN`` (jobs/s from the same
    ``veles_slave_jobs_done_total`` registry counters the runtime
    increments) plus the derived ``dist_scaling_efficiency_nN`` =
    rate(N) / (N x rate(1)). Co-located numpy processes price the
    wire + codec + dispatch path, not device scaling; efficiency
    falling with N is the thread/GIL ceiling the reactor is meant to
    lift, which is exactly why the trajectory is recorded.
    Directional self-check: down = bad for BOTH key families (they
    are throughput/efficiency figures, not byte counts)."""
    rates = {}
    for n in (1, 2, 4, 8):
        key = "dist_scaling_steps_per_sec_n%d" % n
        try:
            _, steps_per_sec = _dist_wire_row(codec, n_slaves=n)
            rates[n] = steps_per_sec
            extra[key] = round(steps_per_sec, 1)
        except Exception as exc:
            extra[key + "_error"] = str(exc)[:200]
    for n in (2, 4, 8):
        if n in rates and rates.get(1):
            extra["dist_scaling_efficiency_n%d" % n] = round(
                rates[n] / (n * rates[1]), 3)


def _xla_throughput(create_workflow, cfg, counter_kind, scale,
                    epochs_per_dispatch, name, measure_chunks=1):
    """Shared build-and-time scaffold: seed, size the dataset via the
    sample's config section, init on the XLA device, time whole
    dispatch chunks; rates come from the telemetry registry's
    ``veles_loader_*`` counters (see ``_train_counter``);
    -> (best, median) count units per second."""
    import veles.prng as prng
    prng.seed_all(99)
    cfg.decision.max_epochs = 1024
    # patience must exceed the chunk size: XLAStep clamps even forced
    # dispatch chunks to fail_iterations - epochs_since_best, so the
    # sample default of 50 silently clips 64-epoch chunks (and shrinks
    # them further as patience drains — ADVICE-grade variance)
    cfg.decision.fail_iterations = 100000
    wf = create_workflow(name=name)
    wf.initialize(device="xla")
    loader, step = wf.loader, wf.xla_step
    step.epochs_per_dispatch = epochs_per_dispatch
    best, median = _timed_chunks(
        loader, step, _train_counter(loader, counter_kind, scale),
        measure_chunks)
    return best, median


def xla_cifar_images_per_sec(measure_chunks=3):
    """Conv-stack throughput (images/sec) on the XLA device."""
    from veles.config import root
    from veles.znicz_tpu.models import cifar10
    root.cifar.loader.update({"minibatch_size": 100, "n_train": 2000,
                              "n_valid": 400})
    # 64 epochs per dispatch: the r3 pin of 16 under-amortized the
    # per-chunk metric fetch on this small model (r4 sweep: 167k at
    # 16, 256k at 64, flat at 128+)
    return _xla_throughput(
        cifar10.create_workflow, root.cifar, "samples", 1,
        epochs_per_dispatch=64, name="BenchCifar",
        measure_chunks=measure_chunks)


def _lm_throughput(loader_cfg, model_cfg, name, epochs_per_dispatch,
                   measure_chunks):
    """Shared LM bench scaffold: save/override/restore the LM config,
    then time dispatch chunks.

    Runs with the engine defaults (bf16 compute + bf16 activation
    policy on TPU): since round 3's mixed-precision policy — bf16
    tensors BETWEEN units, f32 master weights and solver state, f32
    loss/softmax/stat math — bf16 WINS on the 57M LM too (205k vs
    195k tok/s on a v5e; round 2's per-matmul-cast design lost ~4%
    here, which is why it used to pin float32)."""
    from veles.config import root
    from veles.znicz_tpu.models import transformer_lm
    saved_loader = root.lm.loader.to_dict()
    saved_model = root.lm.model.to_dict()
    root.lm.loader.update(loader_cfg)
    root.lm.model.update(model_cfg)
    seq = root.lm.loader.seq_len
    try:
        # tokens/sec = train samples/sec × seq (samples counter from
        # the registry)
        return _xla_throughput(
            transformer_lm.create_workflow, root.lm, "samples", seq,
            epochs_per_dispatch=epochs_per_dispatch, name=name,
            measure_chunks=measure_chunks)
    finally:
        # full restore: every key the overrides touch exists in the
        # sample defaults, so Config.update round-trips cleanly
        root.lm.loader.update(saved_loader)
        root.lm.model.update(saved_model)


def lm_tokens_per_sec(measure_chunks=3):
    """Transformer-LM training throughput (tokens/sec) on the XLA
    device — the north star's NEW config (BASELINE config #5).
    64 epochs per dispatch (r4 sweep: 13.9M tok/s at the old 8,
    21.8M at 64 — the toy model is fetch-amortization-bound)."""
    return _lm_throughput(
        {"minibatch_size": 64, "n_train": 2048, "n_valid": 256,
         "seq_len": 128}, {}, "BenchLM", 64, measure_chunks)


def lm_scale_tokens_per_sec(measure_chunks=3):
    """Transformer-LM throughput at REAL model scale (57.5M params:
    dim 768, 12 heads, 8 layers, ffn 3072, S=512) — the recorded
    large-model number (BASELINE.md 'Transformer LM at scale').
    Config is the measured round-3 optimum from the v5e sweep:
    batch 8 / attn_block 256 (248k median tok/s vs 220k at the old
    batch 16 / block 128)."""
    return _lm_throughput(*LM_ROWS["57M"], "BenchLMScale", 4,
                          measure_chunks)


def lm_base_tokens_per_sec(measure_chunks=3):
    """Transformer-BASE LM throughput (canonical 12-layer config:
    dim 768, 12 heads, ffn 3072, vocab 16384 -> ~110M params with the
    embedding + output head; SURVEY §2.8 "Transformer-base LM" /
    VERDICT r3 weak #5 — the 8-layer 57M flagship under-read it).
    S=512, batch/attn_block from the round-4 v5e sweep."""
    return _lm_throughput(*LM_ROWS["110M"], "BenchLMBase", 4,
                          measure_chunks)


def lm_base_s8k_tokens_per_sec(measure_chunks=3):
    """The 110M transformer-base at S=8192 (long-context row, auto
    impl policy — Pallas flash takes over at this length)."""
    return _lm_throughput(*LM_ROWS["110M_s8k"], "BenchLMBaseLong", 1,
                          measure_chunks)


def lm_longctx_tokens_per_sec(measure_chunks=3):
    """57.5M-param LM at S=8192 (long-context row): blocked attention
    with the AUTO impl policy — the Pallas flash kernels take over at
    this length (measured 2.6x over the XLA scan end-to-end on a v5e;
    ops/attention.py PALLAS_AUTO_MIN_S)."""
    return _lm_throughput(*LM_ROWS["57M_s8k"], "BenchLMLongCtx", 1,
                          measure_chunks)


def lm_345m_tokens_per_sec(measure_chunks=3):
    """~345M-param LM (24 layers, dim 1024, 16 heads, ffn 4096,
    vocab 16384 — GPT-2-medium shape) at S=512: the scale-past-110M
    row VERDICT r4 #4 asked for, batch from the round-5 v5e sweep
    (BASELINE.md)."""
    return _lm_throughput(*LM_ROWS["345M"], "BenchLM345M", 2,
                          measure_chunks)


def serving_throughput_rps(duration=0.6, clients=8,
                           quantize="none"):
    """Inference-path row (ISSUE 1): requests/sec through the
    veles.serving micro-batcher, IN PROCESS (no sockets — this
    measures batching + forward dispatch, not HTTP parsing).

    Builds an un-trained tiny MNIST MLP, exports its archive, loads it
    through the registry on the numpy backend (device-independent: the
    row runs, and means the same thing, with or without a TPU) and
    hammers it from ``clients`` threads of single-sample requests —
    the serving shape where dynamic batching is the whole game.
    ``quantize`` prices the at-rest weight-quantized deployment
    (ISSUE 14): same load, int8/fp8 params densified per dispatch.
    -> (requests/sec, batch_fill_ratio, forward_cache_bytes) — the
    cache figure read from the SAME ``veles_serving_forward_cache_
    bytes`` gauge a /metrics scrape of the process would see."""
    import tempfile
    import threading
    import numpy
    import veles.prng as prng
    prng.seed_all(99)
    from veles import telemetry
    from veles.config import root
    from veles.serving import ModelRegistry
    from veles.znicz_tpu.models import mnist
    saved = {k: root.mnist.loader.get(k)
             for k in ("minibatch_size", "n_train", "n_valid")}
    root.mnist.loader.update({"minibatch_size": 50, "n_train": 200,
                              "n_valid": 50})
    try:
        wf = mnist.create_workflow(name="BenchServe")
        wf.initialize(device="numpy")
        with tempfile.TemporaryDirectory() as tmp:
            wf.export_inference(tmp)
            registry = ModelRegistry(backend="numpy", max_batch=64,
                                     max_queue=4096, max_wait_ms=1.0,
                                     quantize_weights=quantize)
            try:
                # a failed warm/predict used to skip the close and
                # leak the registry's batcher threads for the rest
                # of the bench process (zlint resource-leak)
                entry = registry.load("mnist", tmp)
                x = wf.loader.original_data.mem[:1].astype(
                    numpy.float32)
                entry.predict(x)                  # warm
                cache_bytes = telemetry.get_registry().gauge(
                    "veles_serving_forward_cache_bytes",
                    labels=("model",)).labels("mnist").value
                stop = time.perf_counter() + duration
                counts = [0] * clients

                def client(i):
                    while time.perf_counter() < stop:
                        entry.predict(x, timeout_ms=10000)
                        counts[i] += 1

                threads = [threading.Thread(target=client, args=(i,))
                           for i in range(clients)]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                dt = time.perf_counter() - t0
                fill = entry.batcher.metrics()["batch_fill_ratio"]
            finally:
                registry.close()
        return sum(counts) / dt, fill, cache_bytes
    finally:
        root.mnist.loader.update(saved)


def _routed_http_hammer(base, payload, duration, clients):
    """Hammer one HTTP predict endpoint from ``clients`` threads for
    ``duration`` seconds; -> (requests/sec, sorted latencies). Only
    COMPLETED requests count — a failure mid-window would otherwise
    read as a latency win."""
    import threading
    import urllib.request
    stop = time.perf_counter() + duration
    lats = [[] for _ in range(clients)]

    def client(i):
        while time.perf_counter() < stop:
            t0 = time.perf_counter()
            req = urllib.request.Request(
                base + "/v1/predict", data=payload,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    resp.read()
            except Exception:
                continue
            lats[i].append(time.perf_counter() - t0)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    flat = sorted(v for per in lats for v in per)
    if not flat:
        raise RuntimeError("no routed request completed")
    return len(flat) / dt, flat


def _p99(lats):
    return lats[min(int(len(lats) * 0.99), len(lats) - 1)]


def _wait_ready(url, timeout_s=90.0, path="/readyz"):
    import urllib.request
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url + path, timeout=2):
                return True
        except Exception:
            time.sleep(0.2)
    raise RuntimeError("%s%s never answered 200" % (url, path))


def routed_serving_rows(duration=1.0, clients=4):
    """ISSUE 13 acceptance rows: requests/sec against ONE serving
    replica hit directly over HTTP vs through ``velescli route``'s
    proxy in front of it (proxy overhead, bounded by the >= 0.85x
    acceptance ratio), plus routed p99 with a 2-replica fleet while
    one replica is BROWNED OUT (BrownoutProxy latency + scrape
    timeout -> ejection) next to the healthy-fleet p99 — the router
    must keep the brownout p99 within 2x of healthy.

    Topology is REAL: each replica is a ``velescli serve`` process
    and the overhead row's router is a ``velescli route`` process
    (numpy backend, forced-CPU jax) — co-located single-interpreter
    measurement would price GIL contention between client, router
    and replica threads, not the proxy hop. The brownout pair runs
    the router in-process (identical topology on both sides of THAT
    ratio) because it polls the controller's ejection state
    directly."""
    import tempfile
    import veles.prng as prng
    prng.seed_all(99)
    from veles.chaos import BrownoutProxy
    from veles.config import root
    from veles.router import (FleetController, RouterFrontend,
                              SubprocessExecutor)
    from veles.znicz_tpu.models import mnist
    saved = {k: root.mnist.loader.get(k)
             for k in ("minibatch_size", "n_train", "n_valid")}
    root.mnist.loader.update({"minibatch_size": 50, "n_train": 200,
                              "n_valid": 50})
    velescli = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "velescli.py")
    closers = []
    try:
        wf = mnist.create_workflow(name="BenchRouted")
        wf.initialize(device="numpy")
        x = wf.loader.original_data.mem[:1].astype("float32")
        payload = json.dumps({"model": "mnist",
                              "inputs": x.tolist()}).encode()
        with tempfile.TemporaryDirectory() as tmp:
            wf.export_inference(tmp)
            serve_exec = SubprocessExecutor(
                [sys.executable, velescli, "serve", "--model",
                 "mnist=%s" % tmp, "--backend", "numpy", "--port",
                 "{port}", "--max-wait-ms", "1"],
                start_timeout=120.0, env={"JAX_PLATFORMS": "cpu"})
            closers.append(serve_exec.close)
            url_a = serve_exec.launch()
            url_b = serve_exec.launch()
            if url_a is None or url_b is None:
                raise RuntimeError("replica subprocess never became "
                                   "healthy")
            for url in (url_a, url_b):
                _wait_ready(url)        # model warm, not just alive

            # direct: the single-replica ceiling the proxy is priced
            # against (warm each path before its timed window)
            _routed_http_hammer(url_a, payload, 0.1, 1)
            direct_rps, _ = _routed_http_hammer(
                url_a, payload, duration, clients)

            route_exec = SubprocessExecutor(
                [sys.executable, velescli, "route", url_a, "--port",
                 "{port}", "--interval", "0.3", "--scrape-timeout",
                 "0.5"],
                start_timeout=120.0, env={"JAX_PLATFORMS": "cpu"})
            closers.append(route_exec.close)
            router_url = route_exec.launch()
            if router_url is None:
                raise RuntimeError("router subprocess never became "
                                   "healthy")
            _wait_ready(router_url)     # >= 1 backend admitted
            _routed_http_hammer(router_url, payload, 0.1, 1)
            routed_rps, _ = _routed_http_hammer(
                router_url, payload, duration, clients)

            # 2-replica fleet, one browned out: p99 through the
            # router after ejection vs the healthy-fleet p99
            proxy = BrownoutProxy(
                ("127.0.0.1", int(url_b.rsplit(":", 1)[1])))
            closers.append(proxy.close)
            fleet_ctl = FleetController(
                [url_a, proxy.url], interval=0.3, scrape_timeout=0.5)
            closers.append(fleet_ctl.close)
            fleet_router = RouterFrontend(fleet_ctl, port=0)
            closers.append(fleet_router.close)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and not (
                    # ticks >= 1: the INIT doc already lists both
                    # backends as admitted before any scrape ran
                    fleet_ctl.status_doc["ticks"] >= 1
                    and fleet_ctl.status_doc["admitted"] == 2):
                time.sleep(0.05)
            _routed_http_hammer(fleet_router.url, payload, 0.1, 1)
            _, healthy_lats = _routed_http_hammer(
                fleet_router.url, payload, duration, clients)
            proxy.brownout(2.0)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and not any(
                    b["state"] == "ejected"
                    for b in fleet_ctl.status_doc["backends"]):
                time.sleep(0.05)
            _, brown_lats = _routed_http_hammer(
                fleet_router.url, payload, duration, clients)
        return {"routed_rps_direct": round(direct_rps, 1),
                "routed_rps_via_router": round(routed_rps, 1),
                "routed_p99_healthy_s": round(_p99(healthy_lats), 4),
                "routed_p99_brownout_s": round(_p99(brown_lats), 4)}
    finally:
        for close in reversed(closers):
            try:
                close()
            except Exception:
                pass
        root.mnist.loader.update(saved)


def _routed_rows(extra):
    """Record the router bench guarded (device-independent row).
    Directionality: the rps keys read down = bad (throughput), the
    p99 keys up = bad ("p99" is in _LOWER_BETTER)."""
    try:
        extra.update(routed_serving_rows())
    except Exception as exc:
        extra["routed_rps_error"] = str(exc)[:200]


def _serving_row(extra):
    """Record the serving bench guarded: a failure lands in an _error
    key, never in the exit code (the row must not cost TPU-less runs
    their rc 0)."""
    try:
        rps, fill, cache = serving_throughput_rps()
        extra["serving_throughput_rps"] = round(rps, 1)
        extra["serving_batch_fill_ratio"] = round(fill, 3)
        extra["serving_cache_bytes_f32"] = int(cache)
    except Exception as exc:
        extra["serving_throughput_rps_error"] = str(exc)[:200]


def _quantized_serving_rows(extra):
    """ISSUE 14 acceptance rows: the SAME serving load with int8
    at-rest weights — requests/sec (quantized-vs-f32 throughput as a
    tracked pair; the numpy backend prices the per-dispatch dequant,
    an accelerator fuses it) and the forward-cache shrink, read from
    the same ``veles_serving_forward_cache_bytes`` gauge the runtime
    exports (acceptance: ≤ 55% of the f32 figure). Directionality:
    rps down = bad, bytes up = bad."""
    try:
        rps, _, cache = serving_throughput_rps(quantize="int8")
        extra["serving_throughput_rps_int8"] = round(rps, 1)
        extra["serving_cache_bytes_int8"] = int(cache)
    except Exception as exc:
        # both rows vanish together, so both carry the _error key the
        # trajectory tooling looks for next to a missing row
        extra["serving_throughput_rps_int8_error"] = str(exc)[:200]
        extra["serving_cache_bytes_int8_error"] = str(exc)[:200]


def continual_staleness_s(rounds=2):
    """ISSUE 16 row: end-to-end staleness at the TRAINER point right
    after a continual round completes — seconds between the last
    ingested sample's arrival and "now", with the stream served
    through the real prefetch plane (producer thread, bounded block
    buffer). Steady state for the loop is "this stays near zero"."""
    import numpy
    from veles.loader.stream import ArraySource, ContinualStreamLoader
    from veles.workflow import Workflow
    rng = numpy.random.RandomState(5)
    wf = Workflow(None, name="BenchContinual")
    ld = ContinualStreamLoader(
        wf, name="loader", minibatch_size=32,
        source=ArraySource(
            rng.uniform(-1, 1, (256, 16)).astype(numpy.float32),
            rng.randint(0, 4, 256).astype(numpy.int32)),
        round_samples=128, valid_samples=32)
    try:
        ld.initialize()
        done = 0
        while done < rounds:
            ld.run()
            if bool(ld.epoch_ended):
                done += 1
        return max(0.0, time.time() - ld.last_ingest_wall)
    finally:
        ld.stop()


def rolling_refresh_downtime_s():
    """ISSUE 16 row: wall time of ONE in-place registry hot swap on a
    tiny MNIST model — the window a rolling refresh holds a drained
    replica out of the fleet (the roll itself never fails requests:
    the replica is drained first; this prices how long the roll
    takes per replica)."""
    import tempfile
    import veles.prng as prng
    from veles.config import root
    from veles.serving import ModelRegistry
    from veles.znicz_tpu.models import mnist
    prng.seed_all(41)
    saved = {k: root.mnist.loader.get(k)
             for k in ("minibatch_size", "n_train", "n_valid")}
    root.mnist.loader.update({"minibatch_size": 50, "n_train": 200,
                              "n_valid": 50})
    try:
        wf = mnist.create_workflow(name="BenchRefresh")
        wf.initialize(device="numpy")
        with tempfile.TemporaryDirectory() as tmp:
            wf.export_inference(tmp)
            registry = ModelRegistry(backend="numpy", max_batch=64,
                                     max_queue=256, max_wait_ms=1.0)
            try:
                registry.load("mnist", tmp, warmup=True)
                t0 = time.perf_counter()
                registry.reload("mnist")
                return time.perf_counter() - t0
            finally:
                registry.close()
    finally:
        root.mnist.loader.update(saved)


def _continual_rows(extra):
    """Record the continual-loop pair guarded (device-independent
    rows). Directionality: both are in _LOWER_BETTER — staleness or
    refresh downtime creeping up is the loop decaying."""
    try:
        extra["staleness_seconds_steady_state"] = round(
            continual_staleness_s(), 4)
    except Exception as exc:
        extra["staleness_seconds_steady_state_error"] = str(exc)[:200]
    try:
        extra["rolling_refresh_downtime_s"] = round(
            rolling_refresh_downtime_s(), 4)
    except Exception as exc:
        extra["rolling_refresh_downtime_s_error"] = str(exc)[:200]


def bias_grad_step_seconds(n=65536, k=96, reps=10):
    """ISSUE 14 tentpole row: wall seconds of ONE bias-gradient
    dispatch — relu-derivative mask + f32-accumulating reduction over
    ``n`` batch·space rows × ``k`` channels (a conv1-class shape) —
    through the hand-fused Pallas kernel on a real TPU
    (ops/pallas_grads.py — what the ``fused_bias_grad`` hatch
    dispatches once $VELES_FUSED_BIAS_GRAD=1), the plain masked
    matvec elsewhere (interpret-mode Pallas would time the emulator,
    not the kernel). Scalar readback is the sync point;
    the median of ``reps`` timed calls is returned, so the row is
    comparable round over round per environment."""
    import jax
    import jax.numpy as jnp
    import numpy
    from veles.znicz_tpu.ops import pallas_grads as PG

    gen = numpy.random.Generator(numpy.random.PCG64(17))
    on_tpu = PG._on_tpu()
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    err = jnp.asarray(gen.standard_normal((n, k), numpy.float32), dt)
    y = jnp.asarray(gen.standard_normal((n, k), numpy.float32), dt)
    if on_tpu:
        fn = jax.jit(lambda e, yy: PG.bias_grad(e, yy, "strict_relu"))
    else:
        def plain(e, yy):
            dz = e * (yy > 0).astype(e.dtype)
            return dz.sum(axis=0, dtype=jnp.float32)
        fn = jax.jit(plain)
    float(fn(err, y).sum())                 # compile + warm
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(fn(err, y).sum())             # readback = sync
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def _bias_grad_row(extra):
    try:
        extra["bias_grad_step_seconds"] = round(
            bias_grad_step_seconds(), 6)
    except Exception as exc:
        extra["bias_grad_step_seconds_error"] = str(exc)[:200]


def _lm_decode_export(tmp):
    """Export a tiny LM archive (untrained — decode rows price the
    serving machinery, not model quality) for the generate rows."""
    import veles.prng as prng
    prng.seed_all(99)
    from veles.config import root
    from veles.znicz_tpu.models import transformer_lm
    saved_loader = root.lm.loader.to_dict()
    saved_model = root.lm.model.to_dict()
    root.lm.loader.update({"minibatch_size": 8, "n_train": 64,
                           "n_valid": 16, "seq_len": 16, "vocab": 32,
                           "max_period": 8})
    root.lm.model.update({"dim": 64, "heads": 4, "layers": 2,
                          "ffn_hidden": 128, "moe_experts": 0,
                          "attn_block": None, "attn_impl": None,
                          "stacked": False})
    try:
        wf = transformer_lm.create_workflow(name="BenchDecode")
        wf.initialize(device="numpy")
        wf.export_inference(tmp)
    finally:
        root.lm.loader.update(saved_loader)
        root.lm.model.update(saved_model)


def generate_decode_tokens_per_sec(streams=8, max_tokens=32,
                                   prompt_len=8):
    """ISSUE 11 acceptance rows: aggregate decode tokens/s for
    ``streams`` concurrent generations through the CONTINUOUS batcher
    (shared decode batch, KV slot per stream) vs the same requests
    decoded SEQUENTIALLY one at a time (slot pool of 1 — the same
    machinery, so batch fill is the only difference), plus the median
    submit->first-token latency under the concurrent load. Both
    engines warm one generation first so neither timed row pays an
    XLA compile. -> (sequential tok/s, continuous tok/s, first-token
    median seconds)."""
    import tempfile
    from veles.serving.decode import (ContinuousBatcher,
                                      GenerativeEngine)
    from veles.serving.model import ArchiveModel
    with tempfile.TemporaryDirectory() as tmp:
        _lm_decode_export(tmp)
        model = ArchiveModel.from_dir(tmp)
        prompts = [[(3 * i + j) % 32 for j in range(prompt_len)]
                   for i in range(streams)]

        def run(n_slots, concurrent):
            engine = GenerativeEngine(model, n_slots=n_slots,
                                      max_len=64)
            batcher = ContinuousBatcher(
                engine, max_queue=2 * streams,
                model="bench-decode-%d" % n_slots)
            try:
                # warm: compiles the prompt bucket + the step program
                batcher.generate(prompts[0], max_tokens=4,
                                 wait_s=300)
                t0 = time.perf_counter()
                firsts = []
                if concurrent:
                    handles = [batcher.submit(
                        p, max_tokens=max_tokens) for p in prompts]
                    for h in handles:
                        h.wait(600)
                    firsts = sorted(h.t_first - h.t_submit
                                    for h in handles)
                else:
                    for p in prompts:
                        batcher.generate(p, max_tokens=max_tokens,
                                         wait_s=600)
                dt = time.perf_counter() - t0
            finally:
                batcher.close()
            return streams * max_tokens / dt, firsts

        seq_rate, _ = run(1, False)
        cont_rate, firsts = run(streams, True)
        return seq_rate, cont_rate, \
            firsts[len(firsts) // 2] if firsts else None


def _generate_rows(extra):
    """The decode-plane trajectory (device-independent: numpy-export
    + jax-CPU decode — runs, and means the same thing, with or
    without a TPU). Directional self-check: tokens/s down = bad,
    first-token latency up = bad ("latency" is in _LOWER_BETTER)."""
    try:
        seq, cont, first = generate_decode_tokens_per_sec()
        extra["generate_tokens_per_sec_sequential"] = round(seq, 1)
        extra["generate_tokens_per_sec_continuous"] = round(cont, 1)
        if first is not None:
            extra["generate_first_token_latency_s"] = round(first, 4)
    except Exception as exc:
        extra["generate_tokens_per_sec_error"] = str(exc)[:200]


def lint_full_tree_seconds():
    """Wall time of one full-tree zlint pass over the veles package —
    the analyzer's own cost as a tracked trajectory (up = bad: the
    key contains "seconds", which --self-check reads as
    lower-is-better). The shared-engine refactor is held to < 2x the
    pre-refactor wall time by this row."""
    import veles
    from veles.analysis import analyze_paths
    pkg = os.path.dirname(os.path.abspath(veles.__file__))
    t0 = time.perf_counter()
    findings = analyze_paths([pkg], base=os.path.dirname(pkg))
    dt = time.perf_counter() - t0
    if findings:
        raise RuntimeError(
            "full-tree lint found %d violation(s) — the row would "
            "time a dirty tree" % len(findings))
    return dt


def lint_full_tree_warm_seconds():
    """Wall time of a WARM cached full-tree zlint pass (--cache): a
    priming run fills a fresh cache directory, the timed run answers
    from it. Tracks the incremental-analysis win — the acceptance
    floor is warm <= 50% of cold (up = bad, "seconds" key)."""
    import tempfile

    import veles
    from veles.analysis import analyze_paths
    from veles.analysis.cache import AnalysisCache
    pkg = os.path.dirname(os.path.abspath(veles.__file__))
    base = os.path.dirname(pkg)
    with tempfile.TemporaryDirectory() as tmp:
        cache = AnalysisCache(tmp)
        analyze_paths([pkg], base=base, cache=cache)        # prime
        t0 = time.perf_counter()
        findings = analyze_paths([pkg], base=base,
                                 cache=AnalysisCache(tmp))
        dt = time.perf_counter() - t0
    if findings:
        raise RuntimeError(
            "full-tree lint found %d violation(s) — the row would "
            "time a dirty tree" % len(findings))
    return dt


def _lint_row(extra):
    try:
        extra["lint_full_tree_seconds"] = round(
            lint_full_tree_seconds(), 3)
    except Exception as exc:
        extra["lint_full_tree_seconds_error"] = str(exc)[:200]
    try:
        extra["lint_full_tree_warm_seconds"] = round(
            lint_full_tree_warm_seconds(), 3)
    except Exception as exc:
        extra["lint_full_tree_warm_seconds_error"] = str(exc)[:200]


def _record(extra, key, fn):
    """Run one bench row; primary key = median, ``_best`` = fastest
    chunk (see the module docstring's key convention)."""
    try:
        best, median = fn()
        extra[key] = round(median, 1)
        extra[key + "_best"] = round(best, 1)
    except Exception as exc:   # keep the primary metric robust
        extra[key + "_error"] = str(exc)[:200]


def _device_reachable(timeout_s=240):
    """Probe device init in a daemon thread: a dead TPU tunnel makes
    ``jax.devices()`` HANG (not raise) — observed in round 5 when the
    dev tunnel wedged — and a bench that hangs forever tells the
    driver nothing. Returns (ok, detail)."""
    import threading
    out = {}

    def probe():
        try:
            import jax
            import jax.numpy as jnp
            devs = jax.devices()
            float(jnp.ones((2, 2)).sum())     # readback = real proof
            out["devices"] = str(devs)
        except Exception as exc:
            out["error"] = "%s: %s" % (type(exc).__name__, exc)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return False, "device init did not answer in %ds" % timeout_s
    if "error" in out:
        return False, out["error"]
    return True, out["devices"]


# -- self-check: the bench trajectory as a first-class diff ------------

#: keys where SMALLER is better (wire bytes, profiler overhead,
#: first-token latency, the analyzer's own wall time); everything
#: else numeric in the report is a throughput/efficiency figure where
#: bigger wins
_LOWER_BETTER = ("bytes", "overhead", "latency", "seconds", "p99",
                 "staleness", "downtime", "shed", "rejected")

#: keys where BIGGER is better EVEN IF a lower-better substring ever
#: lands in the same key: an MFU ratio is a utilization figure, down
#: = bad, and an MFU regression must be flagged in its own right —
#: not only via the throughput row it was derived from (ISSUE 14
#: satellite; covered by the directionality fixture in test_health).
#: routed_capacity_rps_at_p99_slo carries "p99" in its name but IS a
#: capacity figure (ISSUE 18's loadgen row): down = bad.
_HIGHER_BETTER = ("mfu", "routed_capacity")

#: keys that are environment stamps, not performance rows
_SELF_CHECK_SKIP = ("calibration",)


def _latest_bench_artifact(directory=None):
    """Newest ``BENCH_r*.json`` next to this file (natural-sorted by
    round number), or None."""
    directory = directory or os.path.dirname(os.path.abspath(__file__))
    def round_no(path):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        return int(m.group(1)) if m else -1
    files = [p for p in glob.glob(os.path.join(directory,
                                               "BENCH_r*.json"))
             if round_no(p) >= 0]
    return max(files, key=round_no) if files else None


def _flatten_rows(report):
    """One {key: number} dict out of a bench report — the primary
    metric under its name plus every numeric ``extra`` row (error
    strings, provenance dicts and *_best duplicates excluded: the
    deltas compare the stable median convention keys)."""
    rows = {}
    if isinstance(report.get("value"), (int, float)) \
            and report.get("metric"):
        rows[str(report["metric"])] = float(report["value"])
    for key, value in (report.get("extra") or {}).items():
        if not isinstance(value, (int, float)) \
                or isinstance(value, bool):
            continue
        if key.endswith("_best") \
                or any(s in key for s in _SELF_CHECK_SKIP):
            continue
        rows[key] = float(value)
    return rows


def self_check(report, threshold_pct=10.0, baseline_path=None,
               stream=None):
    """Compare this run's rows against the latest recorded bench
    artifact and print per-row deltas — WARN-ONLY (the trajectory was
    previously invisible without manually diffing BENCH_r*.json; this
    never changes the exit code or the report). A row regresses when
    it moves more than ``threshold_pct`` percent in its bad direction
    (down for throughput, up for byte counts); -> the regressed keys.
    """
    # resolve the stream at CALL time, never as a parameter default: a
    # def-time ``stream=sys.stderr`` binds whatever object sys.stderr
    # was when this module FIRST imported — under pytest that is the
    # importing test's capture buffer, and every later test's capsys
    # then reads empty (the test_serving-before-test_health order
    # flake, ISSUE 10 satellite)
    if stream is None:
        stream = sys.stderr
    path = baseline_path or _latest_bench_artifact()
    if path is None:
        print("self-check: no BENCH_r*.json baseline found — "
              "nothing to compare", file=stream)
        return []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as exc:
        print("self-check: cannot read %s (%s) — skipped"
              % (path, exc), file=stream)
        return []
    old = _flatten_rows(doc.get("parsed") or doc)
    new = _flatten_rows(report)
    common = sorted(set(old) & set(new))
    if not common:
        print("self-check: no comparable rows vs %s" % path,
              file=stream)
        return []
    print("self-check vs %s (threshold ±%g%%):"
          % (os.path.basename(path), threshold_pct), file=stream)
    regressed = []
    for key in common:
        was, now = old[key], new[key]
        if was == 0:
            continue
        pct = (now - was) / abs(was) * 100.0
        lower_better = (not any(s in key for s in _HIGHER_BETTER)
                        and any(s in key for s in _LOWER_BETTER))
        bad = pct > threshold_pct if lower_better \
            else pct < -threshold_pct
        flag = "  << REGRESSION" if bad else ""
        if bad:
            regressed.append(key)
        print("  %-44s %14.6g -> %14.6g  %+7.1f%%%s"
              % (key, was, now, pct, flag), file=stream)
    dropped = sorted(set(old) - set(new))
    if dropped:
        # a silently vanished row reads as "fine" without this line
        print("  (rows in baseline but not this run: %s)"
              % ", ".join(dropped), file=stream)
    print("self-check: %d row(s) compared, %d regression(s) beyond "
          "±%g%% (warn-only)" % (len(common), len(regressed),
                                 threshold_pct), file=stream)
    return regressed


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="bench.py",
        description="Benchmark entry: prints ONE JSON report line; "
                    "--self-check additionally diffs the rows "
                    "against the latest BENCH_r*.json (warn-only)")
    p.add_argument("--self-check", action="store_true",
                   help="compare this run's rows to the newest "
                        "BENCH_r*.json and print per-row deltas to "
                        "stderr (never changes the exit code)")
    p.add_argument("--self-check-threshold", type=float, default=10.0,
                   metavar="PCT",
                   help="flag rows moving more than PCT%% in their "
                        "bad direction (default 10)")
    p.add_argument("--self-check-baseline", default=None,
                   metavar="PATH",
                   help="explicit baseline artifact (default: "
                        "newest BENCH_r*.json next to bench.py)")
    return p.parse_args(argv)


def main(argv=None):
    args = _parse_args(argv)

    def emit(report, rc=0):
        print(json.dumps(report))
        if args.self_check:
            self_check(report,
                       threshold_pct=args.self_check_threshold,
                       baseline_path=args.self_check_baseline)
        return rc

    ok, detail = _device_reachable()
    if not ok:
        # the serving + wire rows are device-independent: still
        # report them so those trajectories survive tunnel outages
        extra = {"device_error": detail[:300]}
        _serving_row(extra)
        _quantized_serving_rows(extra)
        _continual_rows(extra)
        _bias_grad_row(extra)
        _routed_rows(extra)
        _generate_rows(extra)
        _grad_codec_rows(extra)
        _dist_scaling_rows(extra)
        _profiler_row(extra)
        _model_stats_row(extra)
        _lint_row(extra)
        return emit({
            "metric": "mnist_train_steps_per_sec",
            "value": 0.0,
            "unit": "steps/s",
            "vs_baseline": 0.0,
            "extra": extra,
        }, rc=1)
    extra = {}
    try:
        # calibration FIRST: a fixed device-only matmul rate stamps
        # which tunnel phase this whole run measured in
        extra["calibration_matmul8k_bf16_tflops"] = round(
            device_matmul_tflops(), 1)
    except Exception as exc:
        extra["calibration_error"] = str(exc)[:200]
    base = numpy_steps_per_sec()
    fast, fast_median, grad_bytes = xla_mnist_bench(measure_chunks=3)
    extra.update({
        # the DP all-reduce payload (static param bytes — kept for
        # cross-round comparability) ...
        "grad_sync_bytes_per_step": int(grad_bytes),
        "mnist_numpy_steps_per_sec": round(base, 2),
        "mnist_train_steps_per_sec_best": round(fast, 2),
    })
    # ... and the MEASURED wire bytes per sync, per codec (ISSUE 7)
    _grad_codec_rows(extra)
    # N-slave scaling over the reactor wire plane (ISSUE 9)
    _dist_scaling_rows(extra)
    _record(extra, "cifar_conv_images_per_sec", xla_cifar_images_per_sec)

    def alexnet_row():
        # import inside so ANY failure (import or run) lands in the
        # row's _error key instead of killing the remaining rows
        from bench_alexnet import alexnet_images_per_sec
        median, best = alexnet_images_per_sec()
        return best, median           # _record wants (best, median)

    _record(extra, "alexnet_synth_images_per_sec", alexnet_row)
    _record(extra, "lm_train_tokens_per_sec", lm_tokens_per_sec)
    _record(extra, "lm_57M_tokens_per_sec", lm_scale_tokens_per_sec)
    _record(extra, "lm_57M_s8k_tokens_per_sec",
            lm_longctx_tokens_per_sec)
    _record(extra, "lm_110M_tokens_per_sec", lm_base_tokens_per_sec)
    _record(extra, "lm_110M_s8k_tokens_per_sec",
            lm_base_s8k_tokens_per_sec)
    _record(extra, "lm_345M_tokens_per_sec", lm_345m_tokens_per_sec)
    _serving_row(extra)
    # int8 at-rest weights: quantized-vs-f32 rps + the cache shrink
    # (ISSUE 14; gauge-sourced, acceptance <= 55% of f32)
    _quantized_serving_rows(extra)
    # continual-loop staleness + per-replica refresh downtime
    # (ISSUE 16; both down = good — the loop decays upward)
    _continual_rows(extra)
    # one bias-grad dispatch at a conv1-class shape through the
    # fused_bias_grad auto path (ISSUE 14; up = bad)
    _bias_grad_row(extra)
    # direct vs routed RPS + brownout p99 through the router tier
    # (ISSUE 13; proxy overhead and failover quality as trajectories)
    _routed_rows(extra)
    # continuous-batching decode vs sequential per-request decode
    # (ISSUE 11; the acceptance multiple at 8 concurrent streams)
    _generate_rows(extra)
    # sampling-profiler cost on the same MNIST loop (ISSUE 10; the
    # acceptance bound is < 3% at the default 97 Hz)
    _profiler_row(extra)
    # in-graph model-health stats cost, off-on-off on the XLA chunk
    # loop (ISSUE 15; acceptance < 2%, up = bad)
    _model_stats_row(extra)
    # the analyzer's own full-tree cost (ISSUE 12; up = bad)
    _lint_row(extra)
    # attention-aware MFU for every at-scale LM row (VERDICT r4 #2):
    # median tok/s x train-FLOPs/token over the v5e bf16 peak, shapes
    # read from the SAME LM_ROWS entry the throughput row used
    for row in LM_ROWS:
        _mfu(extra, "lm_%s_tokens_per_sec" % row, "lm_%s_mfu" % row,
             row)
    # the ROADMAP-item-3 headline under its canonical name: the
    # transformer-base long-context MFU (the ~35%-at-S=8192 gap this
    # arc attacks), duplicated from the per-row key so the trajectory
    # has ONE stable handle across config retunes (down = bad)
    if "lm_110M_s8k_mfu" in extra:
        extra["lm_mfu_s8192"] = extra["lm_110M_s8k_mfu"]
    try:
        # calibration AGAIN at the end: a large start/end gap flags a
        # tunnel phase change mid-run (BASELINE.md r4 variance note)
        extra["calibration_matmul8k_bf16_tflops_end"] = round(
            device_matmul_tflops(), 1)
    except Exception as exc:
        extra["calibration_end_error"] = str(exc)[:200]
    # which data fed each number: real on-disk datasets or the
    # synthetic stand-ins (zero-egress environments have no choice,
    # but the record keeps every figure honest — VERDICT r2 item 4)
    from veles.znicz_tpu.models.datasets import data_provenance
    extra["data"] = {k: v.get("source", "?")
                     for k, v in data_provenance().items()}
    # the runtime's own per-step accounting (ISSUE 6 perf ledger,
    # veles/perf.py): recorded in the same artifact so the bench
    # arithmetic and the scraped veles_step_* families can be
    # cross-checked — a walker bug or a dispatch path that skips the
    # ledger shows up as a visible disagreement here
    from veles import telemetry as _telemetry
    _reg = _telemetry.get_registry()
    extra["runtime_step_flops_total"] = int(
        _reg.counter_total("veles_step_flops_total"))
    extra["runtime_step_bytes_total"] = int(
        _reg.counter_total("veles_step_bytes_total"))
    return emit({
        "metric": "mnist_train_steps_per_sec",
        "value": round(fast_median, 2),
        "unit": "steps/s",
        "vs_baseline": round(fast_median / base, 3),
        "extra": extra,
    })


if __name__ == "__main__":
    sys.exit(main())
