"""Fault-tolerance layer under injected faults (ISSUE 2): leases +
fencing, drop→requeue, slave auto-reconnect, the ChaosProxy harness,
and the snapshot store's retry/circuit-breaker degradation.

Everything here is seeded/deterministic in its DECISIONS (what gets
dropped/duplicated is a fixed plan or a seeded PRNG, never wall-clock
luck); assertions are on convergence and counters, not on timing.
"""

import socket
import struct
import threading
import time

import numpy
import pytest

import veles.prng as prng
from veles.chaos import (C2S, S2C, DELAY, DROP, DUP, PASS, TRUNCATE,
                         ChaosEvent, ChaosProxy)
from veles.client import SlaveClient
from veles.distributable import DistributionRegistry
from veles.loader.base import CLASS_TRAIN
from veles.server import MasterServer, recv_frame, send_frame
from tests.test_service import make_wf


def run_iteration(wf):
    """What SlaveClient._run_iteration does on the numpy backend."""
    for u in wf.forwards:
        u.run()
    wf.evaluator.run()
    if wf.loader.minibatch_class == CLASS_TRAIN:
        for gd in reversed(wf.gds):
            gd.run()


def sequential_reference(max_epochs=2):
    """Fault-free single-process run over the exact master job order
    (shuffling disabled on both sides for parity), as in
    test_service.test_single_slave_matches_standalone."""
    ref = make_wf("ChaosRef")
    ref.loader.shuffle_enabled = False
    ref.loader._start_epoch(first=True)
    loader = ref.loader
    for _ in range(max_epochs * loader.effective_batches_per_epoch):
        loader.run()
        run_iteration(ref)
    return numpy.array(ref.forwards[0].weights.map_read().mem)


# -- lease fencing (deterministic, handle-level) -----------------------


def test_unknown_or_revoked_slave_is_fenced():
    """Satellite: job/update/ping from ids not in self.slaves (never
    helloed, or dropped) are rejected, not served/merged."""
    wf = make_wf("FenceUnknown", max_epochs=None)
    wf.decision.max_epochs = 2
    server = MasterServer(wf, "127.0.0.1:0", max_epochs=2)

    assert server.handle(("job", 999, "bogus")) == ("stale",)
    assert server.handle(("ping", 999, "bogus")) == ("stale",)
    assert server.handle(
        ("update", 999, "bogus", 1, 0, {})) == ("stale",)
    assert server.faults["stale_jobs"] == 1
    assert server.faults["stale_pings"] == 1
    assert server.faults["fenced_updates"] == 1

    # a real hello with a WRONG lease id is equally dead (a slave
    # from a previous master incarnation whose id got re-minted)
    kind, sid, lease = server.handle(("hello", "zombie"))
    assert kind == "welcome" and lease
    assert server.handle(("job", sid, "not-the-lease")) == ("stale",)
    assert server.handle(("ping", sid, lease)) == ("pong", 0)

    # dropping the slave revokes the lease outright
    server.drop_slave(sid)
    assert server.faults["drops"] == 1
    assert server.handle(("job", sid, lease)) == ("stale",)


def test_duplicate_update_fenced_weights_identical():
    """Satellite: replaying an already-applied update must leave the
    master weights BITWISE identical — the job_id was consumed, the
    duplicate is fenced instead of double-counted."""
    master_wf = make_wf("FenceMaster", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2)
    _, sid, lease = server.handle(("hello", "fence-slave"))

    slave_wf = make_wf("FenceSlave")
    slave_wf.is_slave = True
    sreg = DistributionRegistry(slave_wf)

    # pull jobs until a TRAIN minibatch (valid/test jobs carry no
    # weight delta, so a double-apply of them would prove nothing)
    loader_name = master_wf.loader.name
    for _ in range(64):
        resp = server.handle(("job", sid, lease))
        assert resp[0] == "job", resp
        _, payload, job_id, epoch = resp[:4]
        if payload[loader_name][0] == CLASS_TRAIN:
            break
    else:
        pytest.fail("no train job served")

    sreg.apply_job(payload)
    run_iteration(slave_wf)
    update = sreg.generate_update()

    assert server.handle(
        ("update", sid, lease, job_id, epoch, update)) == ("ok",)
    w_once = numpy.array(master_wf.forwards[0].weights.map_read().mem)
    # the replay: same lease, same job_id, same bytes
    assert server.handle(
        ("update", sid, lease, job_id, epoch, update)) == ("stale",)
    assert server.faults["fenced_updates"] == 1
    numpy.testing.assert_array_equal(
        master_wf.forwards[0].weights.map_read().mem, w_once)

    # stale-epoch fencing: a job minted now, acknowledged with a wrong
    # epoch tag, is refused too
    resp = server.handle(("job", sid, lease))
    if resp[0] == "job":
        _, payload2, job2, epoch2 = resp[:4]
        assert server.handle(
            ("update", sid, lease, job2, epoch2 + 1, {})) == ("stale",)


def test_mid_job_kill_requeues_and_completes():
    """Satellite: kill a slave mid-job (socket severed, no update) —
    the master requeues its minibatch within the timeout bound and a
    healthy slave finishes the run."""
    master_wf = make_wf("KillMaster", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2,
                          slave_timeout=5.0)
    server.start_background()
    addr = server.bound_address

    # raw-frame slave: hello, take a job, die without updating
    sock = socket.create_connection(addr, timeout=10)
    send_frame(sock, ("hello", "doomed"))
    _, sid, lease = recv_frame(sock)
    send_frame(sock, ("job", sid, lease))
    resp = recv_frame(sock)
    assert resp[0] == "job"
    stolen_job = resp[1][master_wf.loader.name]
    # impolite death: RST, not FIN (SO_LINGER 0)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                    struct.pack("ii", 1, 0))
    sock.close()

    deadline = time.time() + 10
    while time.time() < deadline and server.faults["drops"] < 1:
        time.sleep(0.02)
    st = server.status()
    assert st["faults"]["drops"] >= 1, st
    assert st["faults"]["requeued_jobs"] >= 1, st
    # the stolen minibatch is back at the head of the queue
    assert master_wf.loader._pending_jobs[0] == stolen_job

    healthy = make_wf("KillHealthy")
    healthy.is_slave = True
    client = SlaveClient(healthy, "127.0.0.1:%d" % addr[1],
                         name="healthy", io_timeout=10.0)
    client.run_forever()
    assert server.done.is_set()
    assert server.status()["faults"]["drops"] >= 1


def test_slave_reconnects_through_connection_kill():
    """Auto-reconnect: sever the slave's connection mid-run (via the
    proxy) — run_forever re-hellos on a fresh lease and finishes."""
    master_wf = make_wf("ReconMaster", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2,
                          slave_timeout=5.0)
    server.start_background()

    with ChaosProxy(("127.0.0.1", server.bound_address[1])) as proxy:
        slave_wf = make_wf("ReconSlave")
        slave_wf.is_slave = True
        client = SlaveClient(slave_wf, proxy.address, name="recon",
                             io_timeout=2.0, retry_base=0.02,
                             retry_max=0.2, max_retries=20)
        done = []
        t = threading.Thread(
            target=lambda: done.append(client.run_forever()))
        t.start()
        deadline = time.time() + 30
        while time.time() < deadline and client.jobs_done < 2:
            time.sleep(0.01)
        assert client.jobs_done >= 2, "slave never got going"
        proxy.kill_all()
        t.join(timeout=120)
        assert done, "slave did not survive the kill"
    assert server.done.is_set()
    assert client.reconnects >= 1
    assert server.status()["faults"]["drops"] >= 1


def test_clean_completion_counts_no_faults():
    """A fault-free run must report ZERO drops/fenced updates — the
    counters measure degradation, and a polite bye is not a fault."""
    master_wf = make_wf("CleanMaster", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2)
    server.start_background()
    slave_wf = make_wf("CleanSlave")
    slave_wf.is_slave = True
    SlaveClient(slave_wf, "127.0.0.1:%d" % server.bound_address[1],
                name="clean").run_forever()
    assert server.done.is_set()
    st = server.status()
    assert st["faults"]["drops"] == 0, st
    assert st["faults"]["fenced_updates"] == 0, st
    assert st["faults"]["requeued_jobs"] == 0, st


# -- the acceptance chaos run ------------------------------------------


def _chaos_convergence_two_slaves(codec="none", topk_percent=25.0):
    """2 slaves through a ChaosProxy injecting seeded drops/delays,
    one duplicated update and one mid-job kill — training finishes,
    status() shows >=1 drop and >=1 fenced update, and the final
    master weights match the fault-free single-process UNCOMPRESSED
    run within tolerance (every minibatch merged exactly once;
    under a lossy ``codec``, error feedback must survive retries,
    re-hellos and fencing)."""
    w_ref = sequential_reference(max_epochs=2)

    master_wf = make_wf("ChaosMaster-%s" % codec, max_epochs=None)
    master_wf.loader.shuffle_enabled = False
    master_wf.loader._start_epoch(first=True)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2,
                          slave_timeout=5.0, grad_codec=codec,
                          grad_topk_percent=topk_percent)
    server.start_background()

    lock = threading.Lock()
    seen = {"updates": 0, "jobs": 0, "dup_done": False,
            "kill_done": False}

    def plan(evt):
        with lock:
            if evt.direction == C2S and evt.kind == "update":
                seen["updates"] += 1
                # exactly one duplicated update frame: the fence must
                # keep it from double-counting
                if seen["updates"] == 3 and not seen["dup_done"]:
                    seen["dup_done"] = True
                    return DUP
            if evt.direction == S2C and evt.kind == "job":
                seen["jobs"] += 1
                # exactly one mid-job kill: the job payload dies on
                # the wire, the connection is severed, the master must
                # requeue
                if seen["jobs"] == 5 and not seen["kill_done"]:
                    seen["kill_done"] = True
                    return TRUNCATE
        return None                   # fall through to seeded rates

    with ChaosProxy(("127.0.0.1", server.bound_address[1]), seed=1337,
                    plan=plan, drop_rate=0.01, delay_rate=0.10,
                    delay_s=0.01) as proxy:
        slaves = [make_wf("ChaosSlave%s%d" % (codec, i))
                  for i in range(2)]
        clients = []
        for wf in slaves:
            wf.is_slave = True
        errors = []

        def run_slave(wf, idx):
            client = SlaveClient(
                wf, proxy.address, name="chaos-%d" % idx,
                io_timeout=2.0, retry_base=0.02, retry_max=0.25,
                max_retries=25, grad_codec=codec,
                grad_topk_percent=topk_percent)
            clients.append(client)
            try:
                client.run_forever()
            except ConnectionError:
                # the master tears down after done: a slave caught
                # mid-reconnect is allowed to give up THEN, never
                # before
                if not server.done.is_set():
                    errors.append("gave up before done")

        threads = [threading.Thread(target=run_slave, args=(wf, i))
                   for i, wf in enumerate(slaves)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not errors, errors
        assert server.done.is_set(), server.status()
        stats = proxy.stats()

    st = server.status()
    assert st["faults"]["drops"] >= 1, (st, stats)
    assert st["faults"]["fenced_updates"] >= 1, (st, stats)
    assert st["faults"]["codec_fallbacks"] == 0, st
    assert seen["dup_done"] and seen["kill_done"], (seen, stats)

    w_master = numpy.asarray(
        master_wf.forwards[0].weights.map_read().mem)
    assert numpy.isfinite(w_master).all()
    # exactly-once merge per minibatch: only slave-interleaving (and,
    # under a lossy codec, the bounded residual tail) keeps this from
    # being bitwise
    numpy.testing.assert_allclose(
        w_master, w_ref, atol=0.02,
        err_msg=str({"status": st, "proxy": stats}))
    if codec != "none":
        # the compression REALLY ran through the chaos: every re-
        # hello re-negotiated the codec and the tensor payloads
        # shrank (falsifiable: a silent fallback to 'none' would
        # leave encoded == raw)
        from veles import telemetry
        reg = telemetry.get_registry()
        raw = reg.counter_total("veles_grad_codec_raw_bytes_total",
                                codec=codec)
        enc = reg.counter_total(
            "veles_grad_codec_encoded_bytes_total", codec=codec)
        assert raw > 0, "codec never encoded a tensor"
        assert enc < raw * 0.55, (enc, raw)


def test_chaos_convergence_two_slaves():
    """Acceptance (ISSUE 2): the uncompressed chaos convergence run."""
    _chaos_convergence_two_slaves("none")


@pytest.mark.parametrize("codec", ["int8", "topk"])
def test_chaos_convergence_two_slaves_compressed(codec):
    """Acceptance (ISSUE 7): the same seeded drops/dups/mid-job-kill
    chaos run under a LOSSY gradient codec still lands within the
    existing 2e-2 atol of the fault-free uncompressed run — error
    feedback survives retries, duplicated updates and fencing."""
    _chaos_convergence_two_slaves(codec)


def test_trace_context_propagation_under_chaos():
    """Satellite (ISSUE 6): run 2 slaves through a ChaosProxy with one
    duplicated update and one mid-job kill, tracing enabled on the
    master — the merged trace must stay coherent: every traced span's
    trace_id roots at a ``job.dispatch`` span (no orphans), there is
    exactly ONE ``job.merge`` span per job_id (the duplicated update
    was fenced, not double-merged), and at least one job shows the
    full dispatch → wire → slave-compute → merge causal chain across
    both sides of the wire."""
    from veles import telemetry
    telemetry.tracer.start()
    master_wf = make_wf("TraceChaosMaster", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2,
                          slave_timeout=5.0)
    server.start_background()

    lock = threading.Lock()
    seen = {"updates": 0, "jobs": 0, "dup_done": False,
            "kill_done": False}

    def plan(evt):
        with lock:
            if evt.direction == C2S and evt.kind == "update":
                seen["updates"] += 1
                if seen["updates"] == 3 and not seen["dup_done"]:
                    seen["dup_done"] = True
                    return DUP
            if evt.direction == S2C and evt.kind == "job":
                seen["jobs"] += 1
                if seen["jobs"] == 5 and not seen["kill_done"]:
                    seen["kill_done"] = True
                    return TRUNCATE
        return None

    with ChaosProxy(("127.0.0.1", server.bound_address[1]), seed=4242,
                    plan=plan) as proxy:
        slaves = [make_wf("TraceChaosSlave%d" % i) for i in range(2)]
        for wf in slaves:
            wf.is_slave = True

        def run_slave(wf, idx):
            try:
                SlaveClient(wf, proxy.address, name="trace-%d" % idx,
                            io_timeout=2.0, retry_base=0.02,
                            retry_max=0.25,
                            max_retries=25).run_forever()
            except ConnectionError:
                pass

        threads = [threading.Thread(target=run_slave, args=(wf, i))
                   for i, wf in enumerate(slaves)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert server.done.is_set(), server.status()
    telemetry.tracer.stop()
    assert seen["dup_done"] and seen["kill_done"], seen

    events = telemetry.tracer.events()
    traced = [e for e in events
              if e.get("args", {}).get("trace_id")]
    assert traced, "no trace-context spans recorded"
    roots = {e["args"]["trace_id"] for e in traced
             if e["name"] == "job.dispatch"}
    orphans = [e for e in traced
               if e["args"]["trace_id"] not in roots]
    assert not orphans, orphans[:3]

    merges = [e for e in events if e["name"] == "job.merge"]
    assert merges, "no merge spans"
    merge_jobs = [e["args"]["job_id"] for e in merges]
    assert len(merge_jobs) == len(set(merge_jobs)), \
        "a job_id was merged twice: %s" % sorted(merge_jobs)

    names_by_trace = {}
    for e in traced:
        names_by_trace.setdefault(
            e["args"]["trace_id"], set()).add(e["name"])
    want = {"job.dispatch", "job.wire", "slave.compute", "job.merge"}
    assert any(want <= names for names in names_by_trace.values()), \
        sorted(names_by_trace.values(), key=len)[-1]

    # the wire accounting rode along: both directions moved bytes
    reg = telemetry.get_registry()
    assert reg.counter_total("veles_wire_bytes_total",
                             direction="tx") > 0
    assert reg.counter_total("veles_wire_bytes_total",
                             direction="rx") > 0

    # per-slave latency attribution reached the journal: the merge
    # path filled last-rtt/job/wire for the slaves it heard from
    # (slaves may have deregistered by now, so check via the trace's
    # wire spans instead of status())
    assert any(e["name"] == "job.wire" for e in traced)


def test_status_reports_per_slave_last_job_timing():
    """Satellite: one served+merged job fills the per-slave
    last_rtt_s/last_job_s/last_wire_s fields surfaced by status() —
    slow-slave skew is visible on the dashboard without a trace
    fetch."""
    wf = make_wf("TimingMaster", max_epochs=None)
    wf.decision.max_epochs = 2
    server = MasterServer(wf, "127.0.0.1:0", max_epochs=2)
    _, sid, lease = server.handle(("hello", "timed"))
    st0 = server.status()["slaves"][str(sid)]
    assert st0["last_rtt_s"] is None and st0["last_job_s"] is None

    slave_wf = make_wf("TimingSlave")
    slave_wf.is_slave = True
    sreg = DistributionRegistry(slave_wf)
    resp = server.handle(("job", sid, lease))
    assert resp[0] == "job" and len(resp) >= 5
    # the job frame carries a trace context for the slave's spans
    from veles.telemetry import TraceContext
    assert TraceContext.from_wire(resp[4]) is not None
    _, payload, job_id, epoch = resp[:4]
    sreg.apply_job(payload)
    run_iteration(slave_wf)
    update = sreg.generate_update()
    update["__telemetry__"] = {"token": "t-timing",
                               "job_seconds": 0.004}
    assert server.handle(
        ("update", sid, lease, job_id, epoch, update)) == ("ok",)
    st = server.status()["slaves"][str(sid)]
    assert st["last_rtt_s"] is not None and st["last_rtt_s"] >= 0
    assert st["last_job_s"] == 0.004
    assert st["last_wire_s"] is not None
    assert abs(st["last_wire_s"]
               - max(st["last_rtt_s"] - 0.004, 0)) < 0.002


@pytest.mark.slow
def test_chaos_soak_heavy_rates():
    """Soak variant: sustained seeded drop/dup/delay rates over more
    epochs; completion + exactly-once accounting only (no weight
    parity — requeue reordering compounds)."""
    master_wf = make_wf("SoakMaster", max_epochs=None)
    master_wf.decision.max_epochs = 4
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=4,
                          slave_timeout=5.0)
    server.start_background()
    with ChaosProxy(("127.0.0.1", server.bound_address[1]), seed=99,
                    drop_rate=0.03, dup_rate=0.02, delay_rate=0.2,
                    delay_s=0.02) as proxy:
        slaves = [make_wf("SoakSlave%d" % i) for i in range(3)]
        for wf in slaves:
            wf.is_slave = True

        def run_slave(wf, idx):
            try:
                SlaveClient(wf, proxy.address, name="soak-%d" % idx,
                            io_timeout=2.0, retry_base=0.02,
                            retry_max=0.25,
                            max_retries=50).run_forever()
            except ConnectionError:
                pass
        threads = [threading.Thread(target=run_slave, args=(wf, i))
                   for i, wf in enumerate(slaves)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert server.done.is_set()
        assert proxy.faults_injected() > 0
    w = master_wf.forwards[0].weights.map_read().mem
    assert numpy.isfinite(w).all()


# -- client robustness -------------------------------------------------


def test_connect_rejects_bad_welcome():
    """Satellite: a malformed handshake raises ConnectionError (not a
    bare assert that vanishes under python -O), and a server that
    hangs up mid-handshake does too."""
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen()
    port = listener.getsockname()[1]
    wf = make_wf("BadWelcome")
    wf.is_slave = True

    def serve_one(frame):
        conn, _ = listener.accept()
        recv_frame(conn)
        if frame is not None:
            send_frame(conn, frame)
        conn.close()

    for frame in [("hello", "i-am-not-a-master"), ("welcome", 1),
                  None]:
        t = threading.Thread(target=serve_one, args=(frame,))
        t.start()
        client = SlaveClient(wf, "127.0.0.1:%d" % port,
                             io_timeout=5.0)
        with pytest.raises(ConnectionError):
            client.connect()
        t.join(timeout=10)
    listener.close()


def test_heartbeat_hammers_update_path_without_desync():
    """Satellite (ISSUE 9): the heartbeat thread is SEND-ONLY and
    whole-frame sends are serialized, so pings hammered at ~1kHz
    against a live job/update loop can never interleave bytes
    mid-frame or steal the main reader's responses. A run at this
    ping rate completes with zero reconnects, zero fenced updates and
    zero protocol desyncs — and the pongs owed to the pings are all
    drained by the main reader."""
    master_wf = make_wf("HbHammerMaster", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2,
                          slave_timeout=10.0)
    server.start_background()
    slave_wf = make_wf("HbHammerSlave")
    slave_wf.is_slave = True
    client = SlaveClient(slave_wf,
                         "127.0.0.1:%d" % server.bound_address[1],
                         name="hb-hammer", io_timeout=10.0,
                         ping_interval=0.001)
    jobs = client.run_forever()
    assert server.done.is_set()
    assert jobs > 0
    assert client.pings_sent > 0, \
        "the hammer never hammered — ping_interval not honored"
    # no desync, no reconnect, no fencing: byte-interleaving or a
    # stolen response would show up in every one of these
    assert client.reconnects == 0
    assert client.stale_resyncs == 0
    st = server.status()
    assert st["faults"]["fenced_updates"] == 0, st
    assert st["faults"]["drops"] == 0, st
    # every pong was either drained or is still owed for a ping the
    # final bye cut off — never negative, never unsolicited
    assert client._pending_pongs >= 0


def test_backoff_is_capped_with_jitter():
    wf = make_wf("BackoffWf")
    wf.is_slave = True
    client = SlaveClient(wf, "127.0.0.1:1", retry_base=0.05,
                         retry_max=2.0)
    for attempt in range(1, 12):
        d = client._backoff(attempt)
        assert 0.0 < d <= 2.0 * 1.25
    assert client._backoff(1) <= 0.05 * 1.25
    # retry-forever mode (max_retries=None) runs attempt into the
    # thousands: the exponent must be clamped, not overflow float
    for attempt in (1030, 10 ** 6):
        assert 0.0 < client._backoff(attempt) <= 2.0 * 1.25


def test_slave_request_stop_exits_retry_forever_loop():
    """Preemption relay: request_stop() must break run_forever even
    with max_retries=None and nothing listening (the slave is deep in
    reconnect backoff when SIGTERM arrives)."""
    wf = make_wf("StopWf")
    wf.is_slave = True
    client = SlaveClient(wf, "127.0.0.1:1", io_timeout=0.5,
                         retry_base=0.05, retry_max=5.0,
                         max_retries=None)
    t = threading.Thread(target=client.run_forever, daemon=True)
    t.start()
    time.sleep(0.3)               # let it enter the backoff loop
    client.request_stop()
    t.join(timeout=5)
    assert not t.is_alive()


def test_completed_master_drains_byes_to_stragglers():
    """A run that completes while a slave is disconnected must not
    strand it: the master keeps its listener up for drain_timeout
    answering ("bye",), so a retry-forever slave reconnecting just
    after done still hears the goodbye instead of retrying a dead
    address forever."""
    wf = make_wf("DrainMaster")
    server = MasterServer(wf, "127.0.0.1:0", max_epochs=3,
                          slave_timeout=5.0, drain_timeout=3.0)
    server.start_background()
    server.done.set()
    time.sleep(0.15)              # serve loop enters the drain window
    swf = make_wf("DrainSlave")
    swf.is_slave = True
    client = SlaveClient(swf, "127.0.0.1:%d" % server.bound_address[1],
                         io_timeout=1.0, retry_base=0.02,
                         retry_max=0.2, max_retries=None)
    t = threading.Thread(target=client.run_forever, daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()


def test_client_gives_up_after_max_retries():
    """Capped retries: with nothing listening, run_forever raises
    after max_retries consecutive failures instead of spinning."""
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()                     # nothing listens here now
    wf = make_wf("GiveUpWf")
    wf.is_slave = True
    client = SlaveClient(wf, "127.0.0.1:%d" % dead_port,
                         io_timeout=0.5, retry_base=0.01,
                         retry_max=0.05, max_retries=3)
    with pytest.raises(ConnectionError, match="giving up"):
        client.run_forever()
    assert client.reconnects == 3


# -- ChaosProxy mechanics ----------------------------------------------


def test_chaos_decide_plan_beats_rates_and_is_seeded():
    import random
    proxy = ChaosProxy.__new__(ChaosProxy)    # no sockets needed
    proxy.plan = None
    proxy.drop_rate, proxy.dup_rate = 0.5, 0.5
    proxy.delay_rate = proxy.truncate_rate = 0.0
    evt = ChaosEvent(C2S, 0, 0, "update", 1)
    # seeded rates: same rng seed -> same decision sequence
    a = [proxy._decide(evt, random.Random(7)) for _ in range(5)]
    b = [proxy._decide(evt, random.Random(7)) for _ in range(5)]
    assert a == b and set(a) <= {DROP, DUP}
    # cumulative thresholds exhaust to PASS
    proxy.drop_rate = proxy.dup_rate = 0.0
    assert proxy._decide(evt, random.Random(7)) == PASS
    # an explicit plan wins over any rates
    proxy.plan = lambda e: DELAY
    proxy.drop_rate = 1.0
    assert proxy._decide(evt, random.Random(7)) == DELAY
    proxy.plan = lambda e: "explode"
    with pytest.raises(ValueError):
        proxy._decide(evt, random.Random(7))


def test_chaos_proxy_counts_and_passes_frames():
    """A plain proxied hello/ping round-trip works and is counted."""
    wf = make_wf("ProxyCount", max_epochs=None)
    wf.decision.max_epochs = 2
    server = MasterServer(wf, "127.0.0.1:0", max_epochs=2,
                          slave_timeout=5.0)
    server.start_background()
    with ChaosProxy(("127.0.0.1", server.bound_address[1])) as proxy:
        sock = socket.create_connection(("127.0.0.1", proxy.port),
                                        timeout=10)
        send_frame(sock, ("hello", "count-me"))
        kind, sid, lease = recv_frame(sock)
        assert kind == "welcome"
        send_frame(sock, ("ping", sid, lease))
        assert recv_frame(sock) == ("pong", 0)
        sock.close()
        stats = proxy.stats()
    assert stats["connections"] == 1
    assert stats[C2S][PASS] >= 2 and stats[S2C][PASS] >= 2
    server.done.set()


# -- master restart recovery (ISSUE 4 acceptance) ----------------------


def test_persist_degrades_never_dies(tmp_path, monkeypatch):
    """The 'persistence must degrade, never kill the cluster'
    contract covers STATE BUILD failures too: an exception out of
    checkpoint_state (bad slave-pushed telemetry entry, transient
    device error) must be swallowed into a warning + None, or it
    silently kills the persist thread / crashes the shutdown path."""
    from veles.snapshotter import FileSnapshotStore
    wf = make_wf("PersistWf")
    server = MasterServer(
        wf, "127.0.0.1:0", max_epochs=3,
        checkpoint_store=FileSnapshotStore(str(tmp_path)),
        checkpoint_every=0.05)
    def boom():
        raise RuntimeError("boom")
    monkeypatch.setattr(server, "checkpoint_state", boom)
    assert server.persist_state("test") is None
    assert server.persist_count == 0
    server.done.set()


def test_master_restart_recovery(tmp_path):
    """Acceptance: kill the master mid-run (SIGKILL semantics: no
    goodbye, no final persist), restart it from the store with the
    auto-resume path — slaves reconnect UNAIDED (re-hello against the
    fresh lease table, re-sync via the job payloads) and the final
    weights match the fault-free sequential run within the usual
    tolerance: every minibatch merged exactly once relative to the
    restored state."""
    from veles.snapshotter import FileSnapshotStore, resolve_auto
    w_ref = sequential_reference(max_epochs=3)
    store = FileSnapshotStore(str(tmp_path))

    def spawn_master(resume):
        wf = make_wf("RestartMaster", max_epochs=None)
        wf.loader.shuffle_enabled = False
        wf.loader._start_epoch(first=True)
        wf.decision.max_epochs = 3
        resume_state = None
        if resume:
            resolved = resolve_auto(store)
            assert resolved, "no persisted master state to resume"
            tree, name, _ = resolved
            assert "master" in tree, tree.keys()
            wf.restore_state(tree["workflow"])
            resume_state = tree["master"]
        server = MasterServer(wf, "127.0.0.1:0", max_epochs=3,
                              slave_timeout=5.0,
                              checkpoint_store=store,
                              checkpoint_every=0.02,
                              resume_state=resume_state)
        if resume:
            # the journal actually landed (falsifiable: a restore that
            # silently fell back to construction defaults would not
            # track the persisted counters — which may legitimately
            # still be at 1/0 if the newest persist predates serving,
            # so "made progress" is NOT assertable here)
            assert server.epoch == resume_state["epoch"]
            assert server._next_job == resume_state["next_job"]
        server.start_background()
        return wf, server

    wf1, server1 = spawn_master(resume=False)

    def pace(evt):
        # pace the cluster: ~40ms per served job, so the synthetic
        # workload cannot race from start to done before the test
        # thread (GIL-starved by the in-process cluster) gets to kill
        # the master mid-run (was 20ms; the PR-7 zero-copy framing
        # made the wire fast enough to flake that window)
        if evt.direction == S2C and evt.kind == "job":
            return DELAY
        return None

    with ChaosProxy(("127.0.0.1", server1.bound_address[1]),
                    plan=pace, delay_s=0.04) as proxy:
        clients, errors = [], []

        def run_slave(idx):
            wf = make_wf("RestartSlave%d" % idx)
            wf.is_slave = True
            client = SlaveClient(
                wf, proxy.address, name="restart-%d" % idx,
                io_timeout=1.0, retry_base=0.02, retry_max=0.25,
                max_retries=None)     # a preemptible master's setting
            clients.append(client)
            try:
                client.run_forever()
            except ConnectionError as exc:
                errors.append(str(exc))

        # daemons: these clients retry FOREVER (max_retries=None), so
        # any assertion failing mid-test must not leave pytest waiting
        # on a spinning non-daemon thread for the rest of time
        threads = [threading.Thread(target=run_slave, args=(i,),
                                    daemon=True)
                   for i in range(2)]
        for t in threads:
            t.start()

        # let the cluster make SOME progress and persist at least
        # once, then kill EARLY (most of the run still ahead) so the
        # recovery is substantial, not a formality
        deadline = time.time() + 60
        while time.time() < deadline:
            if server1.persist_count >= 1 \
                    and sum(c.jobs_done for c in clients) >= 4:
                break
            time.sleep(0.005)
        assert server1.persist_count >= 1, "master never persisted"
        assert not server1.done.is_set(), \
            "run finished before the kill — nothing was recovered"

        # SIGKILL: stop serving with NO final persist, sever sockets
        server1.kill()
        proxy.kill_all()

        wf2, server2 = spawn_master(resume=True)
        proxy.target = ("127.0.0.1", server2.bound_address[1])

        assert server2.done.wait(timeout=120), server2.status()
        # slaves caught mid-reconnect when the run completes would
        # retry forever (max_retries=None): cap them so threads exit
        for c in clients:
            c.max_retries = 10
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads)
    # at least one slave re-helloed the restarted master UNAIDED and
    # drove the recovered run to completion (whether the second one
    # makes it back before the work runs out is a scheduling race on
    # this fast synthetic workload, not a robustness property)
    assert server2.faults["joins"] >= 1, server2.status()

    w_master = numpy.asarray(
        wf2.forwards[0].weights.map_read().mem)
    assert numpy.isfinite(w_master).all()
    # weight parity with the fault-free sequential run: the replayed
    # post-persist minibatches run at the restored weights, so a tiny
    # tail of elements drifts marginally past the usual 2e-2 chaos
    # tolerance (measured: <0.004 % of elements, max ~0.023 over 30+
    # runs). Keep 2e-2 as the BULK criterion and cap the tail hard —
    # an accounting bug (lost epoch, double merge) diverges broadly
    # and blows both.
    diff = numpy.abs(w_master - w_ref)
    ctx = str({"status": server2.status(), "errors": errors,
               "max": float(diff.max()),
               "frac>2e-2": float((diff > 0.02).mean())})
    assert diff.max() < 0.05, ctx
    assert (diff > 0.02).mean() < 1e-3, ctx


def test_master_resume_state_fences_old_leases():
    """A restored master must fence every pre-restart identity: the
    lease table starts empty even though slave/job counters continue,
    so a zombie frame can never merge into the recovered weights."""
    wf1 = make_wf("FencePersist", max_epochs=None)
    wf1.decision.max_epochs = 2
    server1 = MasterServer(wf1, "127.0.0.1:0", max_epochs=2)
    _, sid, lease = server1.handle(("hello", "old-slave"))
    resp = server1.handle(("job", sid, lease))
    assert resp[0] == "job"
    state = server1.checkpoint_state()

    wf2 = make_wf("FenceRestored", max_epochs=None)
    wf2.decision.max_epochs = 2
    wf2.restore_state(state["workflow"])
    server2 = MasterServer(wf2, "127.0.0.1:0", max_epochs=2,
                           resume_state=state["master"])
    # the in-flight job was folded back into pending on persist
    assert wf2.loader._pending_jobs[0] == resp[1][wf1.loader.name]
    # the old lease is dead on arrival
    assert server2.handle(("job", sid, lease)) == ("stale",)
    assert server2.handle(
        ("update", sid, lease, resp[2], resp[3], {})) == ("stale",)
    # and a fresh hello mints an id the old incarnation never used
    _, sid2, _ = server2.handle(("hello", "new-slave"))
    assert sid2 > sid


def test_master_resume_empty_queue_does_not_replay_epoch():
    """A persist can land in the window where an epoch is FULLY merged
    (pending and in-flight both empty) but the counter not yet
    advanced (that happens lazily on the next job poll). A restore
    from that state must leave the queue empty — refilling it at the
    stale counter would replay a whole already-merged epoch into the
    restored weights."""
    wf1 = make_wf("EmptyQPersist", max_epochs=None)
    wf1.decision.max_epochs = 3
    server1 = MasterServer(wf1, "127.0.0.1:0", max_epochs=3)
    _, sid, lease = server1.handle(("hello", "sl"))
    while wf1.loader._pending_jobs:
        resp = server1.handle(("job", sid, lease))
        assert resp[0] == "job", resp
        # the payload names the loader, so the in-flight entry clears:
        # a fully MERGED epoch, not just a fully served one
        server1.handle(("update", sid, lease, resp[2], resp[3],
                        {wf1.loader.name: None}))
    state = server1.checkpoint_state()
    assert not state["master"]["pending"]
    assert state["master"]["epoch"] == 0

    wf2 = make_wf("EmptyQRestored", max_epochs=None)
    wf2.decision.max_epochs = 3
    wf2.restore_state(state["workflow"])
    server2 = MasterServer(wf2, "127.0.0.1:0", max_epochs=3,
                           resume_state=state["master"])
    assert server2.epoch == 0
    assert not wf2.loader._pending_jobs   # no refill at the stale counter
    _, sid2, lease2 = server2.handle(("hello", "sl2"))
    assert server2.handle(("job", sid2, lease2)) == ("wait",)
    assert server2.epoch == 1             # advanced, not replayed
    resp = server2.handle(("job", sid2, lease2))
    assert resp[0] == "job" and resp[3] == 1


@pytest.mark.slow
def test_master_sigkill_soak_subprocess(tmp_path):
    """Soak: the full CLI stack — master and slaves as real
    processes, the master SIGKILLed and restarted TWICE with
    ``--snapshot auto`` on the same port; slaves (--slave-retries 0 =
    unbounded) ride through both restarts and the run completes."""
    import os
    import subprocess
    import sys
    from tests.test_service import REPO

    port = _dead_port()
    snapdir = str(tmp_path / "snaps")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    overrides = ["root.mnist.decision.max_epochs=6",
                 "root.mnist.loader.n_train=500",
                 "root.mnist.loader.n_valid=100",
                 "root.mnist.loader.minibatch_size=50"]
    base = [sys.executable, "-m", "veles",
            os.path.join(REPO, "veles/znicz_tpu/models/mnist.py"),
            "--seed", "11", "-d", "numpy", "--no-stats"] + overrides
    master_cmd = base + ["--listen-address", "127.0.0.1:%d" % port,
                         "--snapshots", snapdir,
                         "--checkpoint-every", "0.2",
                         "--slave-timeout", "5"]

    def master_files():
        try:
            return {n for n in os.listdir(snapdir) if "_master-" in n}
        except OSError:
            return set()

    def wait_new_master_file(before, proc, timeout=120):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if master_files() - before:
                return True
            if proc.poll() is not None:
                return False        # master finished on its own
            time.sleep(0.05)
        return False

    procs = []
    try:
        master = subprocess.Popen(master_cmd, cwd=REPO, env=env,
                                  stdout=subprocess.DEVNULL,
                                  stderr=subprocess.DEVNULL)
        procs.append(master)
        slaves = [subprocess.Popen(
            base + ["--master-address", "127.0.0.1:%d" % port,
                    "--slave-retries", "0"],
            cwd=REPO, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL) for _ in range(2)]
        procs += slaves

        for round_ in range(2):
            before = master_files()
            if not wait_new_master_file(before, master):
                # the run may legitimately complete before a second
                # kill window opens; the restart already proved itself
                assert round_ > 0 and master.poll() is not None, \
                    "no master persist before kill %d" % round_
                break
            time.sleep(0.5)       # accumulate some post-persist work
            master.kill()         # SIGKILL: no handler, no goodbye
            master.wait(timeout=30)
            master = subprocess.Popen(
                master_cmd + ["--snapshot", "auto"], cwd=REPO,
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            procs.append(master)

        assert master.wait(timeout=600) == 0
        for slave in slaves:
            assert slave.wait(timeout=120) == 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


# -- snapshot store degradation ----------------------------------------


def _dead_port():
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    return port


def test_http_store_retries_then_breaker_opens():
    from veles.snapshotter import CircuitOpenError, HTTPSnapshotStore
    store = HTTPSnapshotStore(
        "http://127.0.0.1:%d/snaps" % _dead_port(), timeout=0.5,
        retries=1, retry_backoff=0.01, breaker_threshold=2,
        breaker_reset=60.0)
    for _ in range(2):
        with pytest.raises(OSError):
            store.get("x.ckpt.npz.gz")
    m = store.metrics()
    assert m["breaker_open"] and m["breaker_trips"] == 1
    assert m["retries"] >= 2          # each attempt retried once
    # breaker open -> instant fail, no socket work
    t0 = time.monotonic()
    with pytest.raises(CircuitOpenError):
        store.get("x.ckpt.npz.gz")
    assert time.monotonic() - t0 < 0.1
    assert store.metrics()["breaker_fast_fails"] == 1


def test_http_store_breaker_half_open_recovers():
    """After breaker_reset one probe goes through; success closes the
    breaker (and a 5xx-flapping server is retried to success)."""
    import http.server
    import json as _json
    fails = {"n": 2}
    blobs = {"snaps/ok.ckpt.npz": b"payload"}

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if fails["n"] > 0:
                fails["n"] -= 1
                self.send_response(503)
                self.end_headers()
                return
            name = self.path.lstrip("/")
            if name.endswith("/") or not name:
                body = _json.dumps(sorted(blobs)).encode()
            elif name in blobs:
                body = blobs[name]
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        from veles.snapshotter import (CircuitOpenError,
                                       HTTPSnapshotStore)
        url = "http://127.0.0.1:%d/snaps" % httpd.server_address[1]
        # 5xx then success within one call's retry budget
        store = HTTPSnapshotStore(url, timeout=5, retries=3,
                                  retry_backoff=0.01)
        assert store.get("ok.ckpt.npz") == b"payload"
        assert store.metrics()["retries"] == 2
        assert not store.metrics()["breaker_open"]

        # force the breaker open, then let the reset window pass: the
        # half-open probe succeeds and closes it
        store2 = HTTPSnapshotStore(url, timeout=5, retries=0,
                                   breaker_threshold=1,
                                   breaker_reset=0.2)
        fails["n"] = 1
        with pytest.raises(OSError):
            store2.get("ok.ckpt.npz")
        assert store2.breaker_open()
        with pytest.raises(CircuitOpenError):
            store2.get("ok.ckpt.npz")
        time.sleep(0.25)
        # half-open admits exactly one probe: a second caller racing
        # the probe window fast-fails instead of stacking timeouts
        with store2._lock:
            store2._probe_in_flight = True
        with pytest.raises(CircuitOpenError):
            store2.get("ok.ckpt.npz")
        with store2._lock:
            store2._probe_in_flight = False
        assert store2.get("ok.ckpt.npz") == b"payload"
        assert not store2.breaker_open()
        # a 404 is an ANSWER, not a health event: no breaker action
        with pytest.raises(KeyError):
            store2.get("missing.ckpt.npz")
        assert not store2.breaker_open()
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_store_for_shares_breaker_state():
    """store_for caches one HTTPSnapshotStore per base URL so repeated
    checkpoint refreshes share a circuit breaker."""
    from veles.snapshotter import store_for
    url = "http://127.0.0.1:%d/bucket" % _dead_port()
    s1, name1 = store_for(url + "/a.ckpt.npz.gz")
    s2, name2 = store_for(url + "/b.ckpt.npz.gz")
    assert s1 is s2
    assert (name1, name2) == ("a.ckpt.npz.gz", "b.ckpt.npz.gz")


def test_registry_reload_degrades_not_dies():
    """A failed hot reload (source gone / checkpoint store down) keeps
    serving the loaded version and counts the failure."""
    from veles.serving.registry import ModelRegistry

    class FakeEntry:
        name = "m"
        source = "/nonexistent/archive-dir"
        checkpoint = None
        version = 3

    reg = ModelRegistry(backend="numpy")
    entry = FakeEntry()
    reg._models["m"] = entry
    assert reg.reload("m") is entry           # degraded, not raised
    assert reg._refresh_failures["m"] == 1
    assert reg.reload("m") is entry
    assert reg._refresh_failures["m"] == 2


def test_web_status_renders_cluster_faults():
    from veles.web_status import WebStatus
    status = WebStatus(port=0)
    try:
        status.register("cluster", lambda: {
            "mode": "master", "n_slaves": 2,
            "faults": {"drops": 1, "fenced_updates": 2}})
        page = status.render_page()
        assert "n_slaves" in page and "faults" in page
        assert "fenced_updates" in page
    finally:
        status.close()
