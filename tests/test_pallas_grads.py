"""Hand-fused Pallas bias-grad kernel (ops/pallas_grads.py):
exactness pins against the reference ``dz.sum(axis=0)`` math
(interpret mode on CPU; the same kernel runs natively on TPU), and the
``fused_bias_grad`` escape hatch through the dense and conv GD units
at the existing gd tolerances."""

import numpy
import pytest

import veles.prng as prng
from veles.znicz_tpu.ops import activations as A
from veles.znicz_tpu.ops import pallas_grads as PG
from veles.znicz_tpu.ops.all2all import All2AllTanh
from veles.znicz_tpu.ops.conv import ConvRELU

from tests.test_conv_stack import build, xla_backward


def _ref(err, y, act):
    d = A.ACTIVATIONS[act][1](numpy, y)
    dz = err if isinstance(d, float) else err * d
    return dz.sum(axis=0, dtype=numpy.float32)


@pytest.mark.parametrize("act", sorted(A.ACTIVATIONS))
@pytest.mark.parametrize("shape", [(128, 96), (96, 7), (100, 5)],
                         ids=str)
def test_kernel_matches_reference(act, shape):
    """Every activation derivative in the shared table, over
    tile-friendly AND awkward (non-pow2 rows / narrow K) shapes —
    the boundary blocks of the fixed tile are masked in-kernel,
    never a wrong answer."""
    import jax.numpy as jnp
    prng.seed_all(77)
    gen = prng.get("pg")
    err = gen.normal(0, 1.0, shape).astype(numpy.float32)
    y = gen.normal(0, 1.0, shape).astype(numpy.float32)
    got = numpy.asarray(PG.bias_grad(jnp.asarray(err),
                                     jnp.asarray(y), act))
    ref = _ref(err, y, act)
    assert got.shape == ref.shape
    assert numpy.allclose(got, ref, atol=2e-4), \
        (act, numpy.abs(got - ref).max())


def test_kernel_bf16_inputs_f32_accumulate():
    """bf16 err/y (the TPU storage dtype): the kernel converts and
    accumulates in f32 — the whole point — so the result sits within
    bf16 input-rounding error of the f32 reference, not within bf16
    ACCUMULATION error (which at 4096 rows would be ~100x larger)."""
    import jax.numpy as jnp
    prng.seed_all(78)
    gen = prng.get("pg16")
    err = gen.normal(0, 1.0, (4096, 32)).astype(numpy.float32)
    y = gen.normal(0, 1.0, (4096, 32)).astype(numpy.float32)
    eb = jnp.asarray(err, jnp.bfloat16)
    yb = jnp.asarray(y, jnp.bfloat16)
    got = numpy.asarray(PG.bias_grad(eb, yb, "strict_relu"))
    assert got.dtype == numpy.float32
    ref = _ref(numpy.asarray(eb, numpy.float32),
               numpy.asarray(yb, numpy.float32), "strict_relu")
    assert numpy.allclose(got, ref, atol=2e-3), \
        numpy.abs(got - ref).max()


def test_kernel_awkward_row_count_masked_boundary():
    """Row counts with few factors of two (exactly the B·oy·ox conv
    shapes the hatch feeds, e.g. 2700 = 2^2·3^3·5^2) ride the fixed
    512-row tile with an in-kernel mask on the ceil-div boundary
    block — never a degenerate pow2-divisor tile — and stay exact."""
    import jax.numpy as jnp
    prng.seed_all(79)
    gen = prng.get("pg-awkward")
    err = gen.normal(0, 1.0, (2700, 16)).astype(numpy.float32)
    y = gen.normal(0, 1.0, (2700, 16)).astype(numpy.float32)
    for act in ("strict_relu", "linear"):
        got = numpy.asarray(PG.bias_grad(jnp.asarray(err),
                                         jnp.asarray(y), act))
        ref = _ref(err, y, act)
        assert numpy.allclose(got, ref, atol=1e-3), \
            (act, numpy.abs(got - ref).max())


def test_kernel_wide_k_tiles_channels():
    """K beyond the 1024-channel tile (the vocab-wide dense-layer
    case that must NOT claim K·block_n VMEM in one grid step): the
    channel axis rides its own grid dimension — including a K that
    the tile does not divide, whose boundary garbage lands only in
    dropped out-of-bounds output columns."""
    import jax.numpy as jnp
    prng.seed_all(80)
    gen = prng.get("pg-wide")
    for k in (4096, 3000):
        err = gen.normal(0, 1.0, (96, k)).astype(numpy.float32)
        y = gen.normal(0, 1.0, (96, k)).astype(numpy.float32)
        for act in ("strict_relu", "linear"):
            got = numpy.asarray(PG.bias_grad(jnp.asarray(err),
                                             jnp.asarray(y), act))
            assert got.shape == (k,)
            ref = _ref(err, y, act)
            assert numpy.allclose(got, ref, atol=1e-3), \
                (k, act, numpy.abs(got - ref).max())


def test_kernel_rejects_bad_inputs():
    import jax.numpy as jnp
    x = jnp.zeros((8, 4), jnp.float32)
    with pytest.raises(KeyError):
        PG.bias_grad(x, x, "no_such_activation")
    with pytest.raises(ValueError):
        PG.bias_grad(x, jnp.zeros((8, 5), jnp.float32), "linear")
    with pytest.raises(ValueError):
        PG.bias_grad(x, x, "linear", block_n=3)


@pytest.mark.parametrize("cls,kwargs", [
    (ConvRELU, dict(n_kernels=5, kx=3, ky=3, padding=2, sliding=3)),
    (All2AllTanh, dict(output_sample_shape=(7,))),
], ids=lambda v: getattr(v, "__name__", "cfg"))
def test_gd_unit_fused_matches_oracle(cls, kwargs):
    """fused_bias_grad=True (forced through interpret mode on CPU):
    the traced backward's bias update must match the numpy oracle at
    the existing gd tolerances — and stay leaf-identical to the plain
    path on every OTHER parameter (the hatch touches only the bias
    reduction)."""
    shape = (2, 7, 6, 3) if cls is ConvRELU else (16, 12)
    wf, feed, fwd, gd, x, err, comp = build(
        cls, input_shape=shape,
        gd_kwargs={"fused_bias_grad": True}, **kwargs)
    params0 = comp.gather_params()
    state0 = comp.gather_state()
    gd.numpy_run()
    b_np = fwd.bias.map_read().mem.copy()
    w_np = fwd.weights.map_read().mem.copy()
    ei_x, params1 = xla_backward(comp, feed, fwd, gd, params0, state0,
                                 x, err)
    assert numpy.allclose(
        b_np, numpy.asarray(params1[fwd.name]["bias"]), atol=3e-4), \
        numpy.abs(b_np - numpy.asarray(params1[fwd.name]["bias"])).max()
    assert numpy.allclose(
        w_np, numpy.asarray(params1[fwd.name]["weights"]), atol=3e-4)


def test_gd_unit_fused_off_is_default_on_cpu(monkeypatch):
    """Auto policy: the hatch stays closed on a CPU device (the
    pathology is a TPU fusion decision) AND — until a real-TPU window
    validates the kernel end-to-end — without the explicit
    $VELES_FUSED_BIAS_GRAD=1 opt-in even where a TPU would be
    present; bias_grad_xla returns None and the call site keeps the
    plain reduction."""
    wf, feed, fwd, gd, x, err, comp = build(
        ConvRELU, gd_kwargs={}, n_kernels=4, kx=3, ky=3)

    class _Ctx:
        pass

    ctx = _Ctx()
    ctx._compiler = comp
    assert gd.fused_bias_grad is None
    assert gd.bias_grad_xla(ctx, None, None) is None
    # the env opt-in alone is not enough off-TPU either
    monkeypatch.setenv("VELES_FUSED_BIAS_GRAD", "1")
    assert gd.bias_grad_xla(ctx, None, None) is None
    gd.fused_bias_grad = False
    assert gd.bias_grad_xla(ctx, None, None) is None
