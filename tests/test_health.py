"""Cluster health plane (ISSUE 8): the time-series ring, readiness
probes, SLO burn-rate alerts, the fleet scraper (`velescli top`), the
503+Retry-After rejection path, trace-correlated JSONL logs and the
bench self-check — unit level first, then the end-to-end chaos
acceptance run (master + 2 slaves under ChaosProxy).

Determinism: unit-level SLO/ring tests drive ``HealthMonitor.tick``
with injected timestamps (no sampler thread, no wall-clock luck); the
chaos acceptance asserts on convergence of states behind generous
deadlines, never on exact timing.
"""

import json
import logging
import statistics
import threading
import time
import urllib.error
import urllib.request

import numpy
import pytest

from veles import health, telemetry
from veles.health import HealthMonitor


def _get(url, timeout=10):
    """(code, json_doc) — non-200 probe answers carry JSON too."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as exc:
        return exc.code, json.load(exc)


@pytest.fixture
def mnist_config_guard():
    """make_wf (tests/test_service.py) mutates root.mnist without
    restoring; tests here that build workflows must not leak that
    config into later files (test_mnist_functional reads it)."""
    from veles.config import root
    # the sample's module-level defaults must be in root BEFORE the
    # snapshot, or a never-touched key restores as an explicit None
    from veles.znicz_tpu.models import mnist  # noqa: F401
    saved_loader = {k: root.mnist.loader.get(k)
                    for k in ("minibatch_size", "n_train", "n_valid")}
    saved_epochs = root.mnist.decision.get("max_epochs")
    yield
    root.mnist.loader.update(saved_loader)
    root.mnist.decision.max_epochs = saved_epochs


# -- the time-series ring ----------------------------------------------


def test_history_ring_samples_and_windows():
    mon = HealthMonitor(interval=0.5, max_samples=4)
    c = telemetry.counter("veles_serving_shed_total", "x", ("model",))
    g = telemetry.gauge("veles_cluster_slaves", "x")
    h = telemetry.histogram("veles_serving_latency_seconds", "x",
                            ("model",))
    t0 = time.time() - 6
    for i in range(6):
        c.labels("m").inc(2)
        g.set(i)
        h.labels("m").observe(0.01 * (i + 1))
        mon.tick(now=t0 + i)
    doc = mon.history_doc(window=3600)
    # bounded: maxlen=4 kept only the newest 4 ticks (the
    # constructor's own tick was evicted by the ring)
    assert doc["samples"] == 4
    series = doc["series"]
    assert series['veles_serving_shed_total{model="m"}'][-1][1] == 12.0
    assert series["veles_cluster_slaves"][-1][1] == 5.0
    key = 'veles_serving_latency_seconds{model="m"}'
    assert key + ":p50" in series and key + ":p99" in series
    assert series[key + ":count"][-1][1] == 6.0
    # the window filter works off the recorded walls
    mon.close()


def test_history_window_query_filters_by_wall():
    mon = HealthMonitor(interval=0.1, max_samples=100)
    g = telemetry.gauge("veles_cluster_slaves", "x")
    g.set(1)
    mon._samples.clear()        # drop the constructor's own sample
    now = time.time()
    mon.tick(now=now - 30)
    mon.tick(now=now - 1)
    doc = mon.history_doc(window=5)
    assert doc["samples"] == 1          # only the fresh sample
    assert mon.history_doc(window=3600)["samples"] == 2
    mon.close()


def test_series_value_sums_family_children():
    from veles.health import _series_value
    flat = {'veles_serving_shed_total{model="a"}': 3.0,
            'veles_serving_shed_total{model="b"}': 4.0,
            'veles_serving_latency_seconds{model="a"}:p99': 0.5}
    assert _series_value(flat, "veles_serving_shed_total") == 7.0
    assert _series_value(
        flat, 'veles_serving_shed_total{model="b"}') == 4.0
    # percentile keys resolve exactly, and never sum into the family
    assert _series_value(
        flat,
        'veles_serving_latency_seconds{model="a"}:p99') == 0.5
    assert _series_value(flat, "veles_serving_latency_seconds") \
        is None
    assert _series_value(flat, "veles_absent_total") is None
    # label VALUES containing a colon still sum into the family
    # (only the }:pNN suffix keys are excluded)
    colon = {'veles_req_total{endpoint="host:8080"}': 2.0,
             'veles_req_total{endpoint="host:8081"}': 3.0}
    assert _series_value(colon, "veles_req_total") == 5.0


# -- readiness checks --------------------------------------------------


def test_readiness_checks_and_probe_cache():
    mon = HealthMonitor(interval=5.0)
    ok, reasons = mon.ready_state()
    assert ok and reasons == []         # no checks -> ready
    state = {"ok": True}
    mon.add_check("thing", lambda: (state["ok"], None)
                  if state["ok"] else (False, "thing broke"))
    assert mon.ready_state()[0] is True
    state["ok"] = False
    mon.tick()
    ok, reasons = mon.ready_state()
    assert ok is False
    assert any("thing broke" in r for r in reasons)
    code, doc = mon.probe("/readyz")
    assert code == 503 and doc["checks"]["thing"]["ok"] is False
    # a RAISING check degrades to not-ready with the exception named,
    # never kills the tick
    mon.add_check("bad", lambda: 1 / 0)
    ok, reasons = mon.ready_state()
    assert ok is False
    assert any("ZeroDivisionError" in r for r in reasons)
    mon.remove_check("bad")
    state["ok"] = True
    mon.tick()
    assert mon.ready_state()[0] is True
    # liveness flips on shutdown
    assert mon.probe("/healthz")[0] == 200
    mon.mark_shutdown()
    assert mon.probe("/healthz")[0] == 503
    assert mon.ready_state()[0] is False
    mon.close()


# -- SLO engine --------------------------------------------------------


def _slaves_slo(**over):
    spec = {"name": "slaves_floor", "series": "veles_cluster_slaves",
            "op": ">=", "threshold": 2, "target": 0.9,
            "fast_window": 4.0, "slow_window": 12.0,
            "burn_threshold": 1.0}
    spec.update(over)
    return spec


def test_slo_threshold_fires_and_resolves_multi_window():
    mon = HealthMonitor(interval=1.0)
    g = telemetry.gauge("veles_cluster_slaves", "x")
    g.set(2)
    mon.add_slo(_slaves_slo())
    t0 = 5000.0
    for i in range(12):                 # healthy history
        mon.tick(now=t0 + i)
    assert mon.ready_state()[0] is True
    slo = mon.slos()[0]
    assert not slo.firing and slo.burn_fast == 0.0
    # sustained violation: both windows cross the burn threshold
    g.set(1)
    fired_at = None
    for i in range(12, 24):
        mon.tick(now=t0 + i)
        if mon.slos()[0].firing and fired_at is None:
            fired_at = i
    assert fired_at is not None, "alert never fired"
    ok, reasons = mon.ready_state()
    assert ok is False
    assert any("slo:slaves_floor" in r for r in reasons)
    # exported gauges carry the state
    firing = telemetry.gauge(
        "veles_slo_alert_firing",
        labels=("objective",)).labels("slaves_floor")
    assert firing.value == 1.0
    # the transition landed in the flight-recorder event log
    events = [e for e in telemetry.tracer.recent_events()
              if e["event"] == "slo_alert"]
    assert events and events[-1]["state"] == "firing"
    assert events[-1]["objective"] == "slaves_floor"
    # recovery: good samples age the violation out of both windows;
    # the FAST window clears first, which is what ends the alert
    g.set(2)
    resolved_at = None
    for i in range(24, 48):
        mon.tick(now=t0 + i)
        if not mon.slos()[0].firing and resolved_at is None:
            resolved_at = i
    assert resolved_at is not None, "alert never resolved"
    assert firing.value == 0.0
    assert mon.ready_state()[0] is True
    events = [e for e in telemetry.tracer.recent_events()
              if e["event"] == "slo_alert"]
    assert events[-1]["state"] == "resolved"
    mon.close()


def test_slo_ratio_kind_counter_deltas():
    mon = HealthMonitor(interval=1.0)
    bad = telemetry.counter("veles_serving_error_total", "x")
    total = telemetry.counter("veles_serving_requests_total", "x")
    mon.add_slo({"name": "error_ratio", "kind": "ratio",
                 "bad": "veles_serving_error_total",
                 "total": "veles_serving_requests_total",
                 "target": 0.9, "fast_window": 4.0,
                 "slow_window": 8.0, "burn_threshold": 1.0})
    t0 = 9000.0
    for i in range(10):                 # healthy traffic
        total.inc(10)
        mon.tick(now=t0 + i)
    assert not mon.slos()[0].firing
    for i in range(10, 20):             # 50% errors: burn 5x budget
        total.inc(10)
        bad.inc(5)
        mon.tick(now=t0 + i)
    slo = mon.slos()[0]
    assert slo.firing, (slo.burn_fast, slo.burn_slow)
    assert slo.burn_fast == pytest.approx(5.0, rel=0.25)
    for i in range(20, 40):             # clean traffic again
        total.inc(10)
        mon.tick(now=t0 + i)
    assert not mon.slos()[0].firing
    mon.close()


def test_slo_spec_validation_and_file_loading(tmp_path):
    mon = HealthMonitor(interval=5.0)
    with pytest.raises(ValueError):
        mon.add_slo({"series": "x", "threshold": 1})   # no name
    with pytest.raises(ValueError, match="missing required key"):
        mon.add_slo({"name": "p99"})                   # no series
    with pytest.raises(ValueError, match="missing required key"):
        mon.add_slo({"name": "r", "kind": "ratio",
                     "bad": "veles_x_total"})          # no total
    with pytest.raises(ValueError):
        mon.add_slo(_slaves_slo(target=1.5))           # bad target
    with pytest.raises(ValueError):
        mon.add_slo(_slaves_slo(op="~="))              # bad op
    with pytest.raises(ValueError):
        mon.add_slo(_slaves_slo(bogus=1))              # unknown key
    mon.add_slo(_slaves_slo())
    with pytest.raises(ValueError):
        mon.add_slo(_slaves_slo())                     # duplicate
    path = tmp_path / "slos.json"
    path.write_text(json.dumps([
        _slaves_slo(name="from_file"),
        {"name": "ratio_from_file", "kind": "ratio",
         "bad": "veles_serving_error_total",
         "total": "veles_serving_requests_total"},
    ]))
    assert mon.load_slo_file(str(path)) == 2
    assert {s.name for s in mon.slos()} \
        == {"slaves_floor", "from_file", "ratio_from_file"}
    # the readiness doc describes every objective
    doc = mon.probe("/readyz")[1]
    assert set(doc["slos"]) == {s.name for s in mon.slos()}
    mon.close()


# -- serving frontend: rejection + probes ------------------------------


class _ShedModel:
    input_sample_shape = (4,)


class _ShedEntry:
    """Registry entry whose batcher queue is always full."""
    name = "m"
    model = _ShedModel()
    version = 1
    warm = True
    checkpoint = None

    def predict(self, rows, timeout_ms=None, trace=None, tenant=None):
        from veles.serving.batcher import QueueFull
        raise QueueFull("queue full (256 rows pending, max 256)")


def _post_predict(base, doc):
    req = urllib.request.Request(
        base + "/v1/predict", data=json.dumps(doc).encode(),
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, dict(resp.headers), json.load(resp)
    except urllib.error.HTTPError as exc:
        return exc.code, dict(exc.headers), json.load(exc)


def test_frontend_not_ready_503_retry_after_and_counter():
    """Satellite: an empty (cold) registry means /readyz false, and
    POST /v1/predict answers 503 + Retry-After with the reason —
    counted under veles_serving_rejected_total{reason="not_ready"}."""
    from veles.serving.frontend import ServingFrontend
    from veles.serving.registry import ModelRegistry
    with health.scoped(HealthMonitor(interval=30.0)):
        registry = ModelRegistry(backend="numpy")
        front = ServingFrontend(registry, port=0)
        try:
            base = "http://127.0.0.1:%d" % front.port
            code, doc = _get(base + "/readyz")
            assert code == 503
            assert any("no models loaded" in r
                       for r in doc["reasons"])
            code, headers, reply = _post_predict(
                base, {"model": "m", "inputs": [[1, 2, 3, 4]]})
            assert code == 503
            assert headers.get("Retry-After") == "5"
            assert any("no models loaded" in r
                       for r in reply["reasons"])
            reg = telemetry.get_registry()
            assert reg.counter_total("veles_serving_rejected_total",
                                     reason="not_ready") == 1.0
        finally:
            front.close()


def test_frontend_shed_503_retry_after_and_counter():
    """Satellite: a full batcher queue answers 503 + Retry-After and
    counts reason="shed" (previously a generic 503 body only)."""
    from veles.serving.frontend import ServingFrontend
    from veles.serving.registry import ModelRegistry
    with health.scoped(HealthMonitor(interval=30.0)):
        registry = ModelRegistry(backend="numpy")
        registry._models["m"] = _ShedEntry()
        front = ServingFrontend(registry, port=0)
        try:
            base = "http://127.0.0.1:%d" % front.port
            assert _get(base + "/readyz")[0] == 200
            code, headers, reply = _post_predict(
                base, {"model": "m", "inputs": [[1, 2, 3, 4]]})
            assert code == 503
            assert headers.get("Retry-After") == "1"
            assert "queue full" in reply["error"]
            reg = telemetry.get_registry()
            assert reg.counter_total("veles_serving_rejected_total",
                                     reason="shed") == 1.0
        finally:
            front.close()


def test_frontend_history_endpoint_serves_ring():
    from veles.serving.frontend import ServingFrontend
    from veles.serving.registry import ModelRegistry
    with health.scoped(HealthMonitor(interval=30.0)) as mon:
        telemetry.gauge("veles_cluster_slaves", "x").set(3)
        registry = ModelRegistry(backend="numpy")
        front = ServingFrontend(registry, port=0)
        try:
            mon.tick()
            code, doc = _get("http://127.0.0.1:%d"
                             "/metrics/history?window=60" % front.port)
            assert code == 200
            assert doc["series"]["veles_cluster_slaves"][-1][1] == 3.0
        finally:
            front.close()


# -- JSONL log / trace correlation -------------------------------------


def test_jsonl_logs_carry_trace_ids(tmp_path):
    """Satellite: log lines emitted on behalf of a traced request
    carry its trace_id/span_id; unrelated lines don't."""
    from veles.logger import _JsonlHandler
    path = str(tmp_path / "log.jsonl")
    handler = _JsonlHandler(path)
    logger = logging.getLogger("trace-corr-test")
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)
    try:
        ctx = telemetry.TraceContext.new()
        with telemetry.context(ctx):
            logger.info("inside the trace")
        logger.info("outside the trace")
    finally:
        logger.removeHandler(handler)
    rows = [json.loads(line) for line in open(path)]
    assert rows[0]["msg"] == "inside the trace"
    assert rows[0]["trace_id"] == ctx.trace_id
    assert rows[0]["span_id"] == ctx.span_id
    assert "trace_id" not in rows[1]


def test_context_nesting_restores_previous():
    a, b = telemetry.TraceContext.new(), telemetry.TraceContext.new()
    assert telemetry.current_context() is None
    with telemetry.context(a):
        assert telemetry.current_context() is a
        with telemetry.context(b):
            assert telemetry.current_context() is b
        assert telemetry.current_context() is a
    assert telemetry.current_context() is None


# -- fleet scraper / velescli top --------------------------------------


def test_parse_prometheus_exposition():
    from veles.fleet import metric_total, parse_prometheus
    text = "\n".join((
        "# HELP veles_x_total help text",
        "# TYPE veles_x_total counter",
        'veles_x_total{kind="a"} 3',
        'veles_x_total{kind="b",other="q\\"uote"} 4.5',
        "veles_up 1",
        "garbage line without value",
        'veles_lat_bucket{le="+Inf"} 7',
    ))
    m = parse_prometheus(text)
    assert m[("veles_up", ())] == 1.0
    assert m[("veles_x_total", (("kind", "a"),))] == 3.0
    assert metric_total(m, "veles_x_total") == 7.5
    assert metric_total(m, "veles_x_total", kind="b") == 4.5
    assert metric_total(m, "veles_absent") is None
    # escape decoding is one left-to-right pass: an escaped
    # backslash followed by a literal n must NOT become a newline
    esc = parse_prometheus('veles_p{path="C:\\\\new\\nline"} 1')
    assert esc[("veles_p", (("path", "C:\\new\nline"),))] == 1.0


def test_top_json_snapshot_over_live_endpoints(capsys):
    """`velescli top --json` against a live web-status: the snapshot
    names the target, its readiness and the fleet summary."""
    from veles.fleet import top_main
    from veles.web_status import WebStatus
    with health.scoped(HealthMonitor(interval=0.1)) as mon:
        telemetry.gauge("veles_cluster_slaves", "x").set(2)
        mon.tick()
        ws = WebStatus(port=0)
        try:
            base = "http://127.0.0.1:%d" % ws.port
            rc = top_main(["--json", base])
            assert rc == 0
            snap = json.loads(capsys.readouterr().out)
            assert snap["fleet"]["targets"] == 1
            assert snap["fleet"]["reachable"] == 1
            assert snap["fleet"]["ready"] == 1
            assert snap["fleet"]["slaves"] == 2
            row = snap["targets"][0]
            assert row["url"] == base and row["ready"] is True
        finally:
            ws.close()
    # an unreachable fleet exits 2 (scriptable)
    rc = top_main(["--json", "http://127.0.0.1:9/"])
    out = capsys.readouterr().out
    assert rc == 2
    assert json.loads(out)["fleet"]["reachable"] == 0


def test_scrape_degrades_pre_health_plane_target():
    """A live process whose /healthz 404s with a TEXT body (pre-PR-8
    dashboard) must scrape as reachable-but-not-live, never DOWN."""
    import http.server
    from veles.fleet import scrape_target

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = b"not found"
            self.send_response(404)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        row = scrape_target(
            "http://127.0.0.1:%d" % httpd.server_address[1])
        assert row["reachable"] is True
        assert row["live"] is False
        assert row["healthz"] is None
        assert row["ready"] is None     # no /readyz either
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_top_once_renders_dashboard(capsys):
    from veles.fleet import top_main
    from veles.web_status import WebStatus
    with health.scoped(HealthMonitor(interval=0.1)):
        ws = WebStatus(port=0)
        try:
            rc = top_main(["--once",
                           "http://127.0.0.1:%d" % ws.port])
        finally:
            ws.close()
    assert rc == 0
    out = capsys.readouterr().out
    assert "veles fleet" in out and "TARGET" in out


# -- bench self-check --------------------------------------------------


def test_bench_self_check_flags_directional_regressions(tmp_path,
                                                        capsys):
    import bench
    baseline = {
        "n": 1, "rc": 0,
        "parsed": {
            "metric": "mnist_train_steps_per_sec", "value": 1000.0,
            "extra": {
                "cifar_conv_images_per_sec": 200.0,
                "grad_sync_wire_bytes_per_step_int8": 100000,
                "lm_57M_tokens_per_sec": 50000.0,
                "lm_57M_tokens_per_sec_best": 60000.0,
                "calibration_matmul8k_bf16_tflops": 150.0,
                "dist_scaling_steps_per_sec_n2": 100.0,
                "dist_scaling_efficiency_n2": 0.8,
                "profiler_overhead_pct": 1.0,
                "generate_tokens_per_sec_continuous": 4000.0,
                "generate_first_token_latency_s": 0.01,
                "lm_mfu_s8192": 0.35,
                "bias_grad_step_seconds": 0.002,
                "serving_cache_bytes_int8": 200000,
                "serving_throughput_rps_int8": 3000.0,
                "model_stats_overhead_pct": 0.5,
                "loadgen_shed_rate_pct": 1.0,
                "serving_rejected_per_sec": 10.0,
                "routed_capacity_rps_at_p99_slo": 100.0,
                "lint_full_tree_seconds": 10.0,
                "lint_full_tree_warm_seconds": 2.0,
                "some_row_error": "boom",
            }}}
    path = tmp_path / "BENCH_r07.json"
    path.write_text(json.dumps(baseline))
    report = {
        "metric": "mnist_train_steps_per_sec", "value": 800.0,
        "extra": {
            "cifar_conv_images_per_sec": 195.0,       # -2.5%: fine
            "grad_sync_wire_bytes_per_step_int8": 150000,  # +50%: bad
            "lm_57M_tokens_per_sec": 55000.0,         # +10%: fine
            # ISSUE 9: scaling rows are throughput/efficiency figures
            # — DOWN is the bad direction for both families
            "dist_scaling_steps_per_sec_n2": 50.0,    # -50%: bad
            "dist_scaling_efficiency_n2": 0.4,        # -50%: bad
            # ISSUE 10: profiler overhead is a COST — UP is bad
            "profiler_overhead_pct": 2.5,             # +150%: bad
            # ISSUE 11: decode throughput DOWN and first-token
            # latency UP are the bad directions
            "generate_tokens_per_sec_continuous": 2000.0,  # -50%: bad
            "generate_first_token_latency_s": 0.05,        # +400%: bad
            # ISSUE 14: an MFU ratio is a utilization figure — DOWN
            # is bad (explicitly in bench._HIGHER_BETTER, immune to
            # any lower-better substring); kernel step seconds and
            # the quantized cache footprint are costs — UP is bad;
            # quantized serving rps is throughput — DOWN is bad
            "lm_mfu_s8192": 0.20,                          # -43%: bad
            "bias_grad_step_seconds": 0.004,               # +100%: bad
            "serving_cache_bytes_int8": 400000,            # +100%: bad
            "serving_throughput_rps_int8": 3300.0,         # +10%: fine
            # ISSUE 15: in-graph model-stat cost is an overhead — UP
            # is the bad direction ("overhead" is in _LOWER_BETTER)
            "model_stats_overhead_pct": 1.8,               # +260%: bad
            # ISSUE 18: shed/rejected rates are costs — UP is bad;
            # routed capacity carries a "p99" substring but is a
            # capacity figure (bench._HIGHER_BETTER) — DOWN is bad
            "loadgen_shed_rate_pct": 5.0,                  # +400%: bad
            "serving_rejected_per_sec": 20.0,              # +100%: bad
            "routed_capacity_rps_at_p99_slo": 50.0,        # -50%: bad
            # ISSUE 20: lint wall times are costs ("seconds" is in
            # _LOWER_BETTER) — a warm-cache regression means the
            # incremental cache stopped earning its keep
            "lint_full_tree_seconds": 9.0,                 # -10%: fine
            "lint_full_tree_warm_seconds": 6.0,            # +200%: bad
        }}
    regressed = bench.self_check(report, threshold_pct=10.0,
                                 baseline_path=str(path))
    err = capsys.readouterr().err
    # throughput DOWN 20% and byte-count UP 50% regress; the small
    # dip, the improvement, _best and calibration keys don't
    assert set(regressed) == {"mnist_train_steps_per_sec",
                              "grad_sync_wire_bytes_per_step_int8",
                              "dist_scaling_steps_per_sec_n2",
                              "dist_scaling_efficiency_n2",
                              "profiler_overhead_pct",
                              "generate_tokens_per_sec_continuous",
                              "generate_first_token_latency_s",
                              "lm_mfu_s8192",
                              "bias_grad_step_seconds",
                              "serving_cache_bytes_int8",
                              "model_stats_overhead_pct",
                              "loadgen_shed_rate_pct",
                              "serving_rejected_per_sec",
                              "routed_capacity_rps_at_p99_slo",
                              "lint_full_tree_warm_seconds"}
    assert "REGRESSION" in err and "warn-only" in err
    assert "_best" not in err.split("rows in baseline")[0]
    # no baseline -> a note, no crash, nothing regressed
    assert bench.self_check(report, baseline_path=str(
        tmp_path / "missing.json")) == []


def test_bench_latest_artifact_natural_order(tmp_path):
    import bench
    for n in (2, 10, 9):
        (tmp_path / ("BENCH_r%02d.json" % n)).write_text("{}")
    assert bench._latest_bench_artifact(str(tmp_path)).endswith(
        "BENCH_r10.json")
    assert bench._latest_bench_artifact(
        str(tmp_path / "empty")) is None


# -- snapshot-store breaker flips /readyz ------------------------------


def test_readyz_snapshot_breaker_trips_and_recovers(
        mnist_config_guard):
    """Satellite chaos: tripping the master's snapshot-store circuit
    breaker flips /readyz to 503 naming the store; the half-open
    probe closing the breaker flips it back."""
    import http.server
    from veles.snapshotter import HTTPSnapshotStore
    from tests.test_service import make_wf
    from veles.server import MasterServer

    fails = {"n": 0}
    blobs = {"snaps/ok.ckpt.npz": b"payload"}

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if fails["n"] > 0:
                fails["n"] -= 1
                self.send_response(503)
                self.end_headers()
                return
            name = self.path.lstrip("/")
            body = blobs.get(name)
            if body is None:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    server = None
    try:
        url = "http://127.0.0.1:%d/snaps" % httpd.server_address[1]
        store = HTTPSnapshotStore(url, timeout=5, retries=0,
                                  breaker_threshold=1,
                                  breaker_reset=0.2)
        wf = make_wf("BreakerHealthWF", max_epochs=None)
        wf.decision.max_epochs = 50
        server = MasterServer(wf, "127.0.0.1:0", max_epochs=50)
        server.start_background()
        server.checkpoint_store = store
        with health.scoped(HealthMonitor(interval=30.0)) as mon:
            server.register_health(mon)
            assert mon.ready_state()[0] is True
            # trip: one failing GET opens the breaker
            fails["n"] = 1
            with pytest.raises(OSError):
                store.get("ok.ckpt.npz")
            assert store.breaker_open()
            mon.tick()
            ok, reasons = mon.ready_state()
            assert ok is False
            assert any("snapshot-store circuit breaker" in r
                       for r in reasons)
            # recovery: reset window passes, the half-open probe
            # succeeds, the breaker closes
            time.sleep(0.25)
            assert store.get("ok.ckpt.npz") == b"payload"
            assert not store.breaker_open()
            mon.tick()
            assert mon.ready_state()[0] is True
    finally:
        if server is not None:
            server.kill()
        httpd.shutdown()
        httpd.server_close()


# -- end-to-end chaos acceptance ---------------------------------------


def test_cluster_health_chaos_acceptance(capsys,
                                         mnist_config_guard):
    """Acceptance (ISSUE 8): a real master + 2 slaves run under
    ChaosProxy. A mid-job slave kill degrades the slave-floor SLO,
    which fires a burn-rate alert visible in /debug/events and as a
    veles_slo_* gauge, flips /readyz with a reason naming the
    objective, and `velescli top --json` over the live processes
    reports the degraded target; probe endpoints answer fast while
    training is in flight; a replacement slave resolves the alert and
    flips /readyz back."""
    from tests.test_service import make_wf
    from veles.chaos import ChaosProxy
    from veles.client import SlaveClient
    from veles.fleet import parse_prometheus, top_main
    from veles.server import MasterServer
    from veles.web_status import WebStatus

    master_wf = make_wf("HealthChaosMaster", max_epochs=None)
    master_wf.decision.max_epochs = 10000   # outlives the scenario
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=10000,
                          slave_timeout=5.0)
    server.start_background()

    def wait_until(fn, timeout=60, what=""):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            v = fn()
            if v:
                return v
            time.sleep(0.05)
        pytest.fail("timed out waiting for %s" % (what or fn))

    clients, threads = [], []
    ws = proxy = None
    try:
        with health.scoped(HealthMonitor(interval=0.05)) as mon:
            server.register_health(mon)
            ws = WebStatus(port=0)
            ws.register("cluster", server.status)
            base = "http://127.0.0.1:%d" % ws.port
            proxy = ChaosProxy(
                ("127.0.0.1", server.bound_address[1]), seed=7,
                delay_rate=0.05, delay_s=0.01)

            def run_slave(idx, max_retries):
                wf = make_wf("HealthChaosSlave%d" % idx)
                wf.is_slave = True
                client = SlaveClient(
                    wf, proxy.address, name="hc-%d" % idx,
                    io_timeout=2.0, retry_base=0.02, retry_max=0.25,
                    max_retries=max_retries)
                clients.append(client)
                try:
                    client.run_forever()
                except ConnectionError:
                    pass            # the killed slave gives up — the
                                    # scenario under test

            for idx, retries in ((0, None), (1, 0)):
                t = threading.Thread(target=run_slave,
                                     args=(idx, retries))
                t.start()
                threads.append(t)
            wait_until(lambda: server.status()["n_slaves"] == 2,
                       what="both slaves joining")
            # the floor objective goes in once the fleet is at
            # strength; the ring may still hold pre-join samples
            # inside the slow window, so readiness SETTLES to 200 as
            # they age out rather than holding it instantly
            mon.add_slo({"name": "cluster_slaves_floor",
                         "series": "veles_cluster_slaves",
                         "op": ">=", "threshold": 2, "target": 0.9,
                         "fast_window": 0.5, "slow_window": 1.5,
                         "burn_threshold": 1.0})
            wait_until(lambda: _get(base + "/readyz")[0] == 200,
                       timeout=30,
                       what="/readyz settling after both joins")

            # probes answer fast WHILE training is in flight: the
            # handler reads one cached attribute, so even a loaded
            # CI box keeps the median far under the 50ms budget
            for path in ("/healthz", "/readyz"):
                times = []
                for _ in range(20):
                    t0 = time.perf_counter()
                    code, _doc = _get(base + path)
                    times.append(time.perf_counter() - t0)
                    assert code in (200, 503)
                assert statistics.median(times) < 0.05, (path, times)

            # mid-job kill: sever every proxied connection. Slave 1
            # (max_retries=0) dies for good; slave 0 reconnects and
            # keeps training — the cluster runs degraded at 1 < 2
            assert proxy.kill_all() >= 2
            wait_until(
                lambda: not threads[1].is_alive(),
                what="killed slave giving up")
            wait_until(lambda: server.status()["n_slaves"] == 1,
                       what="master dropping the dead slave")

            # the burn-rate alert fires and flips /readyz with a
            # reason naming the objective
            def degraded():
                code, doc = _get(base + "/readyz")
                return (code, doc) if code == 503 else None
            code, doc = wait_until(degraded, timeout=30,
                                   what="/readyz flipping to 503")
            assert any("cluster_slaves_floor" in r
                       for r in doc["reasons"]), doc
            assert doc["slos"]["cluster_slaves_floor"]["firing"]

            # ... visible as a veles_slo_* gauge on /metrics
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as resp:
                metrics = parse_prometheus(
                    resp.read().decode("utf-8", "replace"))
            assert metrics[(
                "veles_slo_alert_firing",
                (("objective", "cluster_slaves_floor"),))] == 1.0

            # ... and in the flight recorder's event log
            events = json.loads(urllib.request.urlopen(
                base + "/debug/events", timeout=10).read())["events"]
            fired = [e for e in events if e["event"] == "slo_alert"
                     and e.get("state") == "firing"]
            assert fired
            assert fired[-1]["objective"] == "cluster_slaves_floor"

            # velescli top --json over the live process reports the
            # degraded target (what an autoscaler would consume)
            rc = top_main(["--json", base])
            assert rc == 0
            snap = json.loads(capsys.readouterr().out)
            assert snap["fleet"]["firing_slos"] \
                == ["cluster_slaves_floor"]
            assert snap["fleet"]["degraded"] == [base]
            row = snap["targets"][0]
            assert row["ready"] is False and row["role"] == "master"
            assert row["master"]["n_slaves"] == 1
            assert any("cluster_slaves_floor" in r
                       for r in row["reasons"])
            # the per-slave timing the master already tracks is
            # merged into the snapshot (the surviving slave's row)
            assert len(row["master"]["slaves"]) == 1

            # the history ring recorded the degradation trajectory
            hist = _get(base + "/metrics/history?window=120")[1]
            slave_series = hist["series"]["veles_cluster_slaves"]
            assert any(v == 2.0 for _, v in slave_series)
            assert any(v == 1.0 for _, v in slave_series)

            # recovery: a replacement slave joins through the proxy;
            # the alert resolves and /readyz flips back to 200
            t = threading.Thread(target=run_slave, args=(2, None))
            t.start()
            threads.append(t)
            wait_until(lambda: server.status()["n_slaves"] == 2,
                       what="replacement slave joining")
            wait_until(lambda: _get(base + "/readyz")[0] == 200,
                       timeout=30, what="/readyz recovering")
            assert not mon.slos()[0].firing
            events = json.loads(urllib.request.urlopen(
                base + "/debug/events", timeout=10).read())["events"]
            assert any(e["event"] == "slo_alert"
                       and e.get("state") == "resolved"
                       for e in events)
    finally:
        server.kill()
        for client in clients:
            client.request_stop()
        if proxy is not None:
            proxy.close()
        for t in threads:
            t.join(timeout=60)
        if ws is not None:
            ws.close()
    assert not any(t.is_alive() for t in threads), \
        "slave thread leaked past the scenario"
