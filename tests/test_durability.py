"""Durable training (ISSUE 4): manifest-verified checkpoints,
interval + shutdown checkpointing, ``--snapshot auto`` fallback past
corruption, retention rebuild after restart, the ``checkpoints`` CLI
audit, and SIGTERM preemption end to end."""

import gzip
import io
import json
import os
import signal
import subprocess
import sys
import time

import numpy
import pytest

import veles.snapshotter as S
from veles import telemetry
from veles.chaos import corrupt_store_entry, flip_bit, truncate_blob
from tests.test_service import REPO, make_wf


# -- manifest integrity ------------------------------------------------


def test_manifest_roundtrip():
    tree = {"params": {"u": {"w": numpy.arange(12.0).reshape(3, 4)}},
            "meta": {"workflow": "m", "epoch": 3}}
    raw = S.dump_checkpoint(tree, slot="current", extra_meta={"x": 1})
    flat, manifest = S.parse_checkpoint(raw, "m.ckpt.npz")
    assert manifest["schema"] == S.SCHEMA_VERSION
    assert manifest["slot"] == "current" and manifest["x"] == 1
    assert manifest["wall_time"] <= time.time()
    assert set(manifest["arrays"]) == set(flat)
    back = S._unflatten_tree(flat)
    numpy.testing.assert_array_equal(back["params"]["u"]["w"],
                                     tree["params"]["u"]["w"])
    assert back["meta"]["epoch"] == 3


def test_manifest_catches_bitflip_in_payload():
    """A single flipped bit in an (uncompressed) array region must
    fail the per-array sha256 — this is the fault class container
    CRCs don't reliably catch once the blob is on a dumb store."""
    tree = {"params": {"u": {"w": numpy.zeros((64, 64))}}}
    raw = S.dump_checkpoint(tree)
    seen = 0
    for seed in range(4):
        try:
            S.parse_checkpoint(flip_bit(raw, seed=seed))
        except S.CorruptCheckpointError:
            seen += 1
    assert seen == 4


def test_parse_rejects_truncated_gzip(tmp_path):
    store = S.FileSnapshotStore(str(tmp_path))
    tree = {"params": {"u": {"w": numpy.ones(128)}}}
    S.write_checkpoint(store, "t_x.ckpt.npz.gz", tree)
    raw = store.get("t_x.ckpt.npz.gz")
    for frac in (0.1, 0.5, 0.9):
        with pytest.raises(S.CorruptCheckpointError):
            S.parse_checkpoint(truncate_blob(raw, frac),
                               "t_x.ckpt.npz.gz")
    # load_snapshot surfaces the same fault class for explicit paths
    store.put("t_x.ckpt.npz.gz", truncate_blob(raw))
    with pytest.raises(S.CorruptCheckpointError):
        S.load_snapshot(os.path.join(str(tmp_path),
                                     "t_x.ckpt.npz.gz"))


def test_file_store_commit_is_atomic(tmp_path):
    """The write-then-rename (now fsynced) leaves either the complete
    blob or nothing — never a .tmp turd a resume could see."""
    store = S.FileSnapshotStore(str(tmp_path))
    uri = store.put("a_x.ckpt.npz", b"payload")
    assert open(uri, "rb").read() == b"payload"
    assert not [n for n in os.listdir(str(tmp_path))
                if n.endswith(".tmp")]

    class Boom(Exception):
        pass

    with pytest.raises(Boom):
        with store.stream("b_x.ckpt.npz"):
            raise Boom()
    assert not os.path.exists(os.path.join(str(tmp_path),
                                           "b_x.ckpt.npz"))
    assert not [n for n in os.listdir(str(tmp_path))
                if n.endswith(".tmp")]


# -- scan / auto-resume ------------------------------------------------


def _mini_tree(tag):
    return {"params": {"u": {"w": numpy.full(8, float(tag))}},
            "meta": {"tag": tag}}


def test_scan_orders_and_classifies(tmp_path):
    store = S.FileSnapshotStore(str(tmp_path))
    S.write_checkpoint(store, "wf_=0.5.ckpt.npz.gz", _mini_tree(1))
    S.write_checkpoint(store, "wf_current-00000001.ckpt.npz.gz",
                       _mini_tree(2))
    # legacy: a pre-manifest blob written the old way
    buf = io.BytesIO()
    numpy.savez(buf, **S._flatten_tree(_mini_tree(0)))
    store.put("wf_legacy.ckpt.npz.gz", gzip.compress(buf.getvalue()))
    # corrupt: bit-flipped newest
    S.write_checkpoint(store, "wf_current-00000002.ckpt.npz.gz",
                       _mini_tree(3))
    corrupt_store_entry(store, "wf_current-00000002.ckpt.npz.gz",
                        "truncate")

    infos = S.scan_checkpoints(str(tmp_path))
    by_status = {}
    for i in infos:
        by_status.setdefault(i.status, []).append(i.name)
    assert len(by_status["valid"]) == 2
    assert by_status["legacy"] == ["wf_legacy.ckpt.npz.gz"]
    assert by_status["corrupt"] == ["wf_current-00000002.ckpt.npz.gz"]
    # newest valid leads
    assert infos[0].name == "wf_current-00000001.ckpt.npz.gz"


def test_auto_resume_falls_back_past_corruption(tmp_path):
    """The acceptance fault: the NEWEST checkpoint is corrupt (both a
    truncated gzip and a bit-flipped payload) — auto-resume must pick
    the previous valid one and count every rejection."""
    store = S.FileSnapshotStore(str(tmp_path))
    S.write_checkpoint(store, "wf_current-00000001.ckpt.npz.gz",
                       _mini_tree(1))
    S.write_checkpoint(store, "wf_current-00000002.ckpt.npz.gz",
                       _mini_tree(2))
    S.write_checkpoint(store, "wf_current-00000003.ckpt.npz.gz",
                       _mini_tree(3))
    corrupt_store_entry(store, "wf_current-00000003.ckpt.npz.gz",
                        "truncate")
    corrupt_store_entry(store, "wf_current-00000002.ckpt.npz.gz",
                        "bitflip", seed=7)

    before = telemetry.get_registry().counter_total(
        "veles_checkpoint_verify_failures_total")
    tree, name, skipped = S.resolve_auto(str(tmp_path))
    assert name == "wf_current-00000001.ckpt.npz.gz"
    assert tree["meta"]["tag"] == 1
    assert skipped == 2
    after = telemetry.get_registry().counter_total(
        "veles_checkpoint_verify_failures_total")
    assert after - before == 2

    # nothing valid at all -> None (fresh start), never an exception
    corrupt_store_entry(store, "wf_current-00000001.ckpt.npz.gz",
                        "truncate")
    assert S.resolve_auto(str(tmp_path)) is None


def test_auto_resume_ignores_legacy(tmp_path):
    store = S.FileSnapshotStore(str(tmp_path))
    buf = io.BytesIO()
    numpy.savez(buf, **S._flatten_tree(_mini_tree(9)))
    store.put("wf_old.ckpt.npz.gz", gzip.compress(buf.getvalue()))
    assert S.resolve_auto(str(tmp_path)) is None


def test_auto_resume_filters_by_workflow_prefix(tmp_path):
    """On a SHARED snapshot directory, --snapshot auto must only
    consider THIS workflow's checkpoints: workflow A resuming "the
    newest blob in the store" must never graft workflow B's newer
    weights onto itself."""
    store = S.FileSnapshotStore(str(tmp_path))
    S.write_checkpoint(store, "wfA_=0.5.ckpt.npz.gz", _mini_tree(1))
    time.sleep(0.02)
    S.write_checkpoint(store, "wfB_=0.4.ckpt.npz.gz", _mini_tree(2))
    tree, name, _ = S.resolve_auto(str(tmp_path), prefixes={"wfA"})
    assert name.startswith("wfA_")
    assert tree["meta"]["tag"] == 1
    # unfiltered call keeps the old "newest wins" behaviour
    _, name, _ = S.resolve_auto(str(tmp_path))
    assert name.startswith("wfB_")
    # a prefix set matching nothing = no verifiable checkpoint
    assert S.resolve_auto(str(tmp_path), prefixes={"wfC"}) is None
    # a workflow whose name merely EXTENDS ours is still foreign: the
    # filter matches "<prefix>_<own stamp>", not a bare startswith
    time.sleep(0.02)
    S.write_checkpoint(store, "wfA_big_current-00000001.ckpt.npz.gz",
                       _mini_tree(3))
    tree, name, _ = S.resolve_auto(str(tmp_path), prefixes={"wfA"})
    assert name.startswith("wfA_=")
    assert tree["meta"]["tag"] == 1
    _, name, _ = S.resolve_auto(str(tmp_path), prefixes={"wfA_big"})
    assert name == "wfA_big_current-00000001.ckpt.npz.gz"


def test_read_side_never_creates_a_missing_store(tmp_path):
    """A typo'd resume/audit path must raise, not be silently created
    and read as "empty store, start fresh" — the loud-failure contract
    of resolve_auto's docstring, enforced end to end."""
    missing = str(tmp_path / "no" / "such" / "dir")
    with pytest.raises(FileNotFoundError):
        S.resolve_auto(missing)
    with pytest.raises(FileNotFoundError):
        S.scan_checkpoints(missing)
    assert not os.path.exists(missing)
    from veles.__main__ import checkpoints_main
    assert checkpoints_main([missing]) == 2
    assert not os.path.exists(missing)
    # the WRITE side (a snapshotter materializing its directory)
    # still creates: first run of a fresh job must not need a mkdir
    S.store_for_base(missing).put("wf_x.ckpt.npz", b"d")
    assert os.path.exists(missing)


# -- interval checkpointing + retention --------------------------------


def test_interval_checkpoints_during_run(tmp_path):
    """End to end: a snapshotter configured with a (tiny) wall-clock
    interval writes rolling ``current`` checkpoints at unit boundaries
    DURING the run, alongside the improvement-gated best ones, each
    slot pruned to its own retention."""
    import veles.prng as prng
    from veles.config import root
    from veles.znicz_tpu.models import mnist
    from veles.znicz_tpu.standard_workflow import StandardWorkflow
    prng.seed_all(555)
    root.mnist.loader.minibatch_size = 50
    root.mnist.loader.n_train = 500
    root.mnist.loader.n_valid = 100
    root.mnist.decision.max_epochs = 2
    wf = StandardWorkflow(
        None, name="IntervalWf", layers=root.mnist.layers,
        loader_factory=lambda w: mnist.MnistLoader(
            w, name="loader", minibatch_size=50),
        decision_config=root.mnist.decision.to_dict(),
        snapshotter_config={"directory": str(tmp_path),
                            "interval": 1e-6, "keep_interval": 2})
    wf.initialize(device="numpy")
    wf.run()
    names = S.FileSnapshotStore(str(tmp_path)).list()
    current = [n for n in names if "_current-" in n]
    best = [n for n in names if "_current-" not in n]
    assert current, names
    assert len(current) <= 2            # keep_interval retention
    assert best, names                  # improvement gate still fires
    # the rolling slot is resumable
    tree, name, _ = S.resolve_auto(str(tmp_path))
    assert "_current-" in name or "=" in name
    wf2 = make_wf("IntervalResume", max_epochs=3)
    wf2.restore_state(tree)
    wf2.run()
    assert wf2.decision.epoch_number == 3


def test_interval_failure_waits_full_interval_to_retry(tmp_path):
    """A transient store outage must not burn the 3-strike failure
    budget in back-to-back unit boundaries: the wall-clock gate
    re-arms BEFORE the attempt, so a failed interval write retries
    one interval later, not at the very next run()."""
    wf = make_wf("RetryWf", snapdir=str(tmp_path))
    snap = wf.snapshotter
    snap.interval = 3600.0            # no second attempt inside test
    snap._last_write -= 7200.0        # gate open NOW
    def broken_stream(name):
        raise OSError("store down")
    snap.store.stream = broken_stream
    assert not bool(getattr(wf.decision, "improved", False))
    for _ in range(5):                # 5 unit boundaries, 1 outage
        snap.run()
    assert snap._store_failures == 1, snap._store_failures


def test_retention_rebuilt_from_store_after_restart(tmp_path):
    """Satellite: ``_written`` used to be in-memory only, so a resumed
    process never pruned its predecessor's snapshots. A fresh
    snapshotter over the same store must adopt and keep pruning."""
    wf = make_wf("RetA", snapdir=str(tmp_path))
    snap = wf.snapshotter
    for i in range(3):
        wf.decision.best_metric = 0.5 - 0.1 * i
        snap.export_snapshot()
        snap.export_snapshot(slot="current")
    store = S.FileSnapshotStore(str(tmp_path))
    assert len([n for n in store.list() if "_current-" in n]) == 2

    # "restart": a fresh workflow + snapshotter over the same store
    wf2 = make_wf("RetA", snapdir=str(tmp_path))
    snap2 = wf2.snapshotter
    assert snap2._written, "retention forgot pre-restart snapshots"
    for i in range(3):
        wf2.decision.best_metric = 0.1 - 0.01 * i
        snap2.export_snapshot()
        snap2.export_snapshot(slot="current")
    names = store.list()
    best = [n for n in names if "_current-" not in n]
    current = [n for n in names if "_current-" in n]
    assert len(best) <= snap2.keep, names
    assert len(current) <= snap2.keep_interval, names
    # the rolling sequence continued rather than restarting at 1
    assert any("_current-0000000%d." % i in n
               for n in current for i in (5, 6)), names


def test_checkpoint_telemetry_recorded(tmp_path):
    store = S.FileSnapshotStore(str(tmp_path))
    S.write_checkpoint(store, "wf_x.ckpt.npz.gz", _mini_tree(1),
                       slot="best")
    S.write_checkpoint(store, "wf_current-00000001.ckpt.npz.gz",
                       _mini_tree(2), slot="current")
    reg = telemetry.get_registry()
    assert reg.counter_total("veles_checkpoint_writes_total",
                             slot="best") == 1
    assert reg.counter_total("veles_checkpoint_writes_total",
                             slot="current") == 1
    assert reg.counter_total("veles_checkpoint_bytes_total") > 0
    hist = reg.histogram("veles_checkpoint_write_seconds",
                         labels=("slot",)).labels("best")
    assert hist.count == 1
    age = reg.gauge("veles_checkpoint_last_success_age_seconds").value
    assert 0.0 <= age < 60.0
    # and it renders as a scrape-able exposition
    text = reg.render_prometheus()
    assert "veles_checkpoint_writes_total" in text
    assert "veles_checkpoint_last_success_age_seconds" in text


# -- rollback round-trip (satellite) -----------------------------------


def test_rollback_state_survives_checkpoint_resume(tmp_path):
    """NNRollback history (rollback count, best loss) and the lr cuts
    it applied must survive a FULL checkpoint+resume cycle into a
    fresh process-like workflow — not just a same-process restore."""
    wf = make_wf("RbSrc", snapdir=str(tmp_path))
    rb = wf.link_rollback()
    rb.rollback_count = 2
    rb._best_loss = 0.321
    for gd in wf.gds:
        gd.lr_scale = 0.25
    path = wf.snapshotter.export_snapshot()
    assert path

    wf2 = make_wf("RbDst", max_epochs=3)
    rb2 = wf2.link_rollback()
    wf2.restore_state(S.load_snapshot(path))
    assert rb2.rollback_count == 2
    assert abs(rb2._best_loss - 0.321) < 1e-12
    assert all(gd.lr_scale == 0.25 for gd in wf2.gds)
    state = rb2.get_state()
    assert state == {"rollback_count": 2, "best_loss": 0.321}
    wf2.run()                     # and the resumed run still trains
    assert wf2.decision.epoch_number == 3


def test_aux_unit_state_survives_nn_checkpoint(tmp_path):
    """NNWorkflow.checkpoint_state used to carry ONLY the units it
    knows by name (decision/loader/rollback/params) — any other
    stateful unit was silently dropped and restarted from constructor
    defaults on resume (the exact hole the zlint checkpoint-state rule
    closes statically). An ImageSaver's epoch-directory counter must
    round-trip."""
    from veles.znicz_tpu.image_saver import ImageSaver
    wf = make_wf("AuxSrc", snapdir=str(tmp_path))
    saver = ImageSaver(wf, name="image_saver",
                       out_dir=str(tmp_path / "dumps"))
    saver._epoch = 5
    saver._saved_this_epoch = 3
    saver.total_saved = 41
    tree = wf.checkpoint_state()
    assert tree["units"]["image_saver"] == {
        "epoch": 5, "saved_this_epoch": 3, "total_saved": 41}

    wf2 = make_wf("AuxDst", max_epochs=3)
    saver2 = ImageSaver(wf2, name="image_saver",
                        out_dir=str(tmp_path / "dumps"))
    wf2.restore_state(tree)
    assert (saver2._epoch, saver2._saved_this_epoch,
            saver2.total_saved) == (5, 3, 41)
    # explicitly-handled units must NOT be duplicated under "units"
    assert "decision" not in tree.get("units", {})
    assert "loader" not in tree.get("units", {})


# -- generic workflow checkpoint fallback ------------------------------


def test_plain_workflow_checkpoint_state():
    from veles.units import Unit
    from veles.workflow import Workflow

    class Counting(Unit):
        def __init__(self, workflow, **kw):
            super().__init__(workflow, **kw)
            self.count = 0

        def run(self):
            self.count += 1

        def get_state(self):
            return {"count": self.count}

        def set_state(self, state):
            self.count = int(state["count"])

    wf = Workflow(None, name="PlainWf")
    unit = Counting(wf, name="counting")
    unit.count = 7
    tree = wf.checkpoint_state()
    assert tree["units"]["counting"] == {"count": 7}

    wf2 = Workflow(None, name="PlainWf2")
    unit2 = Counting(wf2, name="counting")
    wf2.restore_state(tree)
    assert unit2.count == 7
    # unknown units in the tree are skipped, not fatal
    tree["units"]["ghost"] = {"count": 1}
    wf2.restore_state(tree)


# -- checkpoints CLI audit (satellite) ---------------------------------


def test_checkpoints_cli_audit(tmp_path, capsys):
    from veles.__main__ import checkpoints_main
    store = S.FileSnapshotStore(str(tmp_path))
    S.write_checkpoint(store, "wf_=0.2.ckpt.npz.gz", _mini_tree(1))
    buf = io.BytesIO()
    numpy.savez(buf, **S._flatten_tree(_mini_tree(0)))
    store.put("wf_old.ckpt.npz.gz", gzip.compress(buf.getvalue()))
    assert checkpoints_main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "valid" in out and "legacy" in out

    S.write_checkpoint(store, "wf_current-00000009.ckpt.npz.gz",
                       _mini_tree(2))
    corrupt_store_entry(store, "wf_current-00000009.ckpt.npz.gz",
                        "truncate")
    assert checkpoints_main(["--json", str(tmp_path)]) == 1
    rows = json.loads(capsys.readouterr().out)
    assert {r["status"] for r in rows} == {"valid", "legacy",
                                           "corrupt"}
    corrupt = [r for r in rows if r["status"] == "corrupt"][0]
    assert corrupt["error"]


# -- SIGTERM preemption end to end -------------------------------------


def test_sigterm_preemption_and_auto_resume(tmp_path):
    """Drive the real CLI: SIGTERM mid-run stops at a unit boundary,
    writes a final checkpoint, exits EXIT_PREEMPTED; a second run with
    ``--snapshot auto`` resumes from the store and completes."""
    from veles.launcher import EXIT_PREEMPTED
    snapdir = tmp_path / "snaps"
    result = tmp_path / "result.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    base = [sys.executable, "-m", "veles",
            os.path.join(REPO, "veles/znicz_tpu/models/mnist.py"),
            "--seed", "7", "-d", "numpy", "--no-stats",
            "--snapshots", str(snapdir),
            "root.mnist.loader.n_train=2000",
            "root.mnist.loader.n_valid=200",
            "root.mnist.loader.minibatch_size=50"]
    proc = subprocess.Popen(
        base + ["--checkpoint-every", "0.2",
                "root.mnist.decision.max_epochs=500"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            if snapdir.is_dir() and any(
                    "_current-" in n for n in os.listdir(str(snapdir))):
                break
            if proc.poll() is not None:
                pytest.fail("run ended before any interval checkpoint:"
                            " %s" % proc.stderr.read()[-2000:])
            time.sleep(0.05)
        else:
            pytest.fail("no interval checkpoint appeared")
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        proc.kill()
    assert rc == EXIT_PREEMPTED, proc.stderr.read()[-2000:]
    infos = S.scan_checkpoints(str(snapdir))
    assert any(i.status == "valid" for i in infos), infos

    out = subprocess.run(
        base + ["--snapshot", "auto", "--result-file", str(result),
                "root.mnist.decision.max_epochs=1"],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    data = json.loads(result.read_text())
    assert data["history"], data
