"""Pluggable normalizer family (SURVEY.md §2.3) + weight diversity
diagnostics (§2.4)."""

import os

import numpy
import pytest

import veles.prng as prng
from veles.config import root
from veles.normalization import NORMALIZERS, factory


@pytest.fixture
def data(rng):
    return rng.normal(3.0, 2.0, (50, 12)).astype(numpy.float32)


def test_registry_names():
    assert {"none", "linear", "range_linear", "mean_disp",
            "pointwise", "external_mean"} <= set(NORMALIZERS)


def test_linear_global_range(data):
    n = factory("linear")
    n.analyze(data[:25])
    n.analyze(data[25:])       # streaming accumulation
    out = n.normalize(data)
    assert out.min() == pytest.approx(-1.0, abs=1e-6)
    assert out.max() == pytest.approx(1.0, abs=1e-6)


def test_range_linear_fixed():
    n = factory("range_linear", source_range=(0, 255))
    out = n.normalize(numpy.array([0.0, 127.5, 255.0]))
    numpy.testing.assert_allclose(out, [-1.0, 0.0, 1.0], atol=1e-6)


def test_mean_disp_per_feature(data):
    n = factory("mean_disp")
    n.analyze(data)
    out = n.normalize(data)
    numpy.testing.assert_allclose(out.mean(axis=0),
                                  numpy.zeros(12), atol=1e-4)
    # centered on the MEAN, scaled by half the range: |out| < 2
    assert numpy.abs(out).max() <= 2.0 + 1e-5
    mean, rdisp = n.mean_rdisp(data.shape[1:])
    numpy.testing.assert_allclose((data - mean) * rdisp, out,
                                  atol=1e-5)


def test_pointwise_constant_feature(data):
    data[:, 0] = 7.0           # constant feature must not blow up
    n = factory("pointwise")
    n.analyze(data)
    out = n.normalize(data)
    assert numpy.all(out[:, 0] == 0.0)
    assert out[:, 1:].min() == pytest.approx(-1.0, abs=1e-6)
    assert out[:, 1:].max() == pytest.approx(1.0, abs=1e-6)


def test_external_mean():
    mean = numpy.full(4, 10.0, numpy.float32)
    n = factory("external_mean", mean=mean, scale=0.5)
    out = n.normalize(numpy.full((2, 4), 12.0))
    numpy.testing.assert_allclose(out, 1.0)


def test_state_roundtrip(data):
    n = factory("mean_disp")
    n.analyze(data)
    n.normalize(data)
    n2 = factory("mean_disp")
    n2.set_state(n.state())
    numpy.testing.assert_array_equal(n2.normalize(data),
                                     n.normalize(data))


def test_affine_probe_matches(data):
    """Base mean_rdisp derives (mean, rdisp) for any affine member."""
    n = factory("linear")
    n.analyze(data)
    mean, rdisp = n.mean_rdisp(data.shape[1:])
    numpy.testing.assert_allclose((data - mean) * rdisp,
                                  n.normalize(data), atol=1e-4)


def test_unknown_type_raises():
    with pytest.raises(KeyError, match="unknown normalization_type"):
        factory("bogus")


# -- loader integration -----------------------------------------------


def test_fullbatch_loader_normalizes():
    prng.seed_all(606)
    from veles.znicz_tpu.models import mnist
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    root.mnist.loader.update(
        {"n_train": 200, "n_valid": 80, "minibatch_size": 40})
    root.mnist.decision.max_epochs = 2
    try:
        wf_raw = mnist.create_workflow(name="NormOff")
        wf_raw.initialize(device="numpy")
        wf = mnist.StandardWorkflow(
            None, name="NormOn", layers=root.mnist.layers,
            loader_factory=lambda w: mnist.MnistLoader(
                w, name="loader",
                minibatch_size=root.mnist.loader.minibatch_size,
                normalization_type="mean_disp"),
            decision_config=root.mnist.decision.to_dict())
        wf.initialize(device="numpy")
        d = wf.loader.original_data.mem
        train0 = wf.loader.class_offset(2)
        # train rows are centered; raw data was not
        assert abs(d[train0:].mean()) < 0.05
        assert abs(wf_raw.loader.original_data.mem.mean()) > 0.05
        wf.run()
        hist = [h["validation"]["metric"]
                for h in wf.decision.history]
        assert hist[-1] <= hist[0]
    finally:
        root.mnist.loader.update(saved)
        root.mnist.decision.max_epochs = 5


def test_externally_assigned_data_is_normalized(rng):
    """Originals set BEFORE initialize (the documented FullBatchLoader
    pattern) must be normalized too."""
    from veles.loader.fullbatch import FullBatchLoader
    from veles.workflow import Workflow
    wf = Workflow(None, name="ExtNorm")
    ld = FullBatchLoader(wf, name="loader", minibatch_size=10,
                         normalization_type="linear")
    data = rng.uniform(0, 255, (30, 8)).astype(numpy.float32)
    ld.original_data.mem = data.copy()
    ld.class_lengths = [0, 10, 20]
    ld.initialize()
    d = ld.original_data.mem
    # stats fit on TRAIN rows only: those map exactly into [-1, 1];
    # eval rows may poke slightly past
    train = d[10:]
    assert train.min() == pytest.approx(-1.0, abs=1e-5)
    assert train.max() == pytest.approx(1.0, abs=1e-5)
    assert d.min() >= -1.2 and d.max() <= 1.2
    # idempotent on re-initialize (snapshot resume path)
    ld.initialize()
    numpy.testing.assert_array_equal(ld.original_data.mem, d)


def test_streaming_loader_rejects_normalizer(rng):
    """Loaders without the hook must fail loudly, not silently train
    on raw data."""
    from veles.loader.stream import ArrayStreamLoader
    from veles.workflow import Workflow
    wf = Workflow(None, name="StreamNorm")
    ld = ArrayStreamLoader(wf, name="loader", minibatch_size=10,
                           normalization_type="mean_disp")
    ld.data = rng.uniform(0, 1, (30, 8)).astype(numpy.float32)
    ld.labels = numpy.zeros(30, numpy.int32)
    ld.class_lengths = [0, 10, 20]
    with pytest.raises(NotImplementedError, match="normalization"):
        ld.initialize()


def test_normalizer_state_rides_checkpoints(tmp_path):
    """Fitted stats survive snapshot -> restore (the inference-only
    restore path can then normalize without train data)."""
    prng.seed_all(909)
    from veles.snapshotter import load_snapshot
    from veles.znicz_tpu.models import mnist
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    saved_epochs = root.mnist.decision.get("max_epochs")
    root.mnist.loader.update(
        {"n_train": 200, "n_valid": 80, "minibatch_size": 40})
    root.mnist.decision.max_epochs = 2
    try:
        wf = mnist.StandardWorkflow(
            None, name="NormSnap", layers=root.mnist.layers,
            loader_factory=lambda w: mnist.MnistLoader(
                w, name="loader", minibatch_size=40,
                normalization_type="mean_disp"),
            decision_config=root.mnist.decision.to_dict(),
            snapshotter_config={"directory": str(tmp_path),
                                "export_inference":
                                    str(tmp_path / "archive")})
        wf.initialize(device="numpy")
        wf.run()
        mean = wf.loader.normalizer.mean.copy()
        assert wf.snapshotter.destination
        # improved snapshots also refreshed the inference archive
        assert os.path.exists(
            str(tmp_path / "archive" / "contents.json"))

        state = load_snapshot(wf.snapshotter.destination)
        wf2 = mnist.create_workflow(name="NormSnap2")
        wf2.initialize(device="numpy")
        wf2.restore_state(state)
        # the checkpoint's mean_disp normalizer replaced the default
        numpy.testing.assert_allclose(
            wf2.loader.normalizer.mean, mean, atol=1e-6)

        # inference-only restore: no train rows to re-fit from — the
        # restored stats must still TRANSFORM the resident data
        from veles.loader.fullbatch import FullBatchLoader
        from veles.workflow import Workflow
        wf3 = Workflow(None, name="InferOnly")
        ld = FullBatchLoader(wf3, name="loader", minibatch_size=10,
                             normalization_type="mean_disp")
        gen = numpy.random.default_rng(5)
        eval_data = gen.normal(3.0, 2.0, (20, 784)) \
            .astype(numpy.float32)
        ld.original_data.mem = eval_data.copy()
        ld.class_lengths = [0, 20, 0]
        ld.initialize()              # fit deferred: no train rows
        ld.set_state(state["loader"])
        expected = (eval_data - wf.loader.normalizer.mean) \
            * wf.loader.normalizer.rdisp
        numpy.testing.assert_allclose(ld.original_data.mem, expected,
                                      atol=1e-5)
    finally:
        root.mnist.loader.update(saved)
        root.mnist.decision.max_epochs = saved_epochs


# -- diversity --------------------------------------------------------


def test_diversity_stats_flags_duplicates():
    from veles.znicz_tpu.diversity import diversity_stats
    rng = numpy.random.default_rng(4)
    w = rng.normal(0, 1, (6, 20)).astype(numpy.float32)
    w[3] = w[0] * 2.0          # duplicate direction
    w[5] = 0.0                 # dead filter
    stats = diversity_stats(w)
    assert stats["n_units"] == 6
    assert stats["similar_pairs"] >= 1
    assert stats["dead_units"] == 1
    assert stats["max_abs_similarity"] >= 0.99


def test_weight_diversity_unit(tmp_path):
    prng.seed_all(707)
    from veles.znicz_tpu.diversity import WeightDiversity
    from veles.znicz_tpu.models import mnist
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    root.mnist.loader.update(
        {"n_train": 200, "n_valid": 80, "minibatch_size": 40})
    root.mnist.decision.max_epochs = 2
    try:
        wf = mnist.create_workflow(name="DivWF")
        div = WeightDiversity(wf, name="diversity",
                              out_dir=str(tmp_path))
        div.link_from(wf.decision)
        div.gate_skip = ~wf.decision.epoch_ended
        wf._end_point_last()
        wf.initialize(device="cpu")
        wf.run()
    finally:
        root.mnist.loader.update(saved)
        root.mnist.decision.max_epochs = 5
    assert div.stats is not None and len(div.history) == 2
    assert div.stats["n_units"] == 100
    import os
    assert os.path.exists(str(tmp_path / "diversity.png"))
