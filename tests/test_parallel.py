"""Distribution layer tests on the 8-device virtual CPU mesh
(SURVEY.md §4 "TPU build translation"): DP training, ring attention
numerics + gradients, grad-sync metric."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root


def test_data_parallel_training_matches_single():
    import jax
    from veles.znicz_tpu import parallel

    def train(dp):
        prng.seed_all(99)
        from veles.znicz_tpu.models import mnist
        root.mnist.loader.update({"minibatch_size": 64,
                                  "n_train": 512, "n_valid": 128})
        root.mnist.decision.max_epochs = 3
        wf = mnist.create_workflow(name="DP%d" % dp)
        wf.initialize(device="cpu")
        if dp:
            parallel.setup_data_parallel(
                wf, parallel.make_mesh({"data": 8}))
        wf.run()
        return wf.decision.history[-1]["validation"]["metric"]

    err_dp = train(True)
    err_single = train(False)
    assert abs(err_dp - err_single) < 0.03, (err_dp, err_single)


def test_data_parallel_conv_non_divisible_minibatch():
    """Scan-mode DP pads the minibatch dim to a multiple of the mesh
    data axis; conv/pool/GD units must reshape by TRACED batch dims,
    not the host-initialized Array shapes (ADVICE r1: minibatch 12 on
    an 8-device mesh pads to 16 and used to fail at trace time)."""
    from veles.znicz_tpu import parallel

    prng.seed_all(7)
    from veles.znicz_tpu.models import cifar10
    root.cifar.loader.update({"minibatch_size": 12,
                              "n_train": 48, "n_valid": 24})
    root.cifar.decision.max_epochs = 1
    wf = cifar10.create_workflow(name="DPConvPad")
    wf.initialize(device="cpu")
    parallel.setup_data_parallel(wf, parallel.make_mesh({"data": 8}))
    wf.run()
    assert wf.decision.history, "no epochs completed"


def test_grad_sync_bytes():
    from veles.znicz_tpu import parallel
    params = {"layer": {
        "w": numpy.zeros((784, 100), numpy.float32),
        "b": numpy.zeros(100, numpy.float32)}}
    assert parallel.grad_sync_bytes(params) == (784 * 100 + 100) * 4


def dense_attention(q, k, v, causal):
    import jax.numpy as jnp
    dh = q.shape[-1]
    s = (q @ jnp.swapaxes(k, -1, -2)) / numpy.sqrt(dh)
    if causal:
        n = q.shape[2]
        mask = numpy.triu(numpy.full((n, n), -1e9, numpy.float32), 1)
        s = s + mask
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return p @ v


#: inner-block kernels for the ring steps: None = fused dense block,
#: "scan" = flash.py blocked formulation, "pallas" = the TPU kernels
#: (interpret mode on the CPU mesh)
RING_INNERS = [None, "scan", "pallas"]


@pytest.mark.parametrize("inner", RING_INNERS,
                         ids=["dense", "scan", "pallas"])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal, inner):
    import jax
    import jax.numpy as jnp
    from veles.znicz_tpu import parallel
    from veles.znicz_tpu.parallel import ring

    mesh = parallel.make_mesh({"seq": 8})
    gen = prng.get("ring")
    b, h, s, dh = 2, 2, 32, 8
    q = jnp.asarray(gen.normal(0, 1.0, (b, h, s, dh)))
    k = jnp.asarray(gen.normal(0, 1.0, (b, h, s, dh)))
    v = jnp.asarray(gen.normal(0, 1.0, (b, h, s, dh)))
    # jit-wrapped like the production path (XLAStep traces the ring
    # INSIDE one step program): eagerly, every one of the ring's
    # hundreds of small multi-device ops compiles and dispatches its
    # own SPMD program — measured 12s/case vs ~1s jitted, pure test
    # overhead with no coverage behind it
    out, lse = jax.jit(lambda a, b, c: ring.ring_self_attention(
        a, b, c, mesh, causal=causal, inner=inner, block=2))(q, k, v)
    ref = dense_attention(q, k, v, causal)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(ref),
                          atol=2e-5), \
        numpy.abs(numpy.asarray(out) - numpy.asarray(ref)).max()


@pytest.mark.parametrize("inner", RING_INNERS,
                         ids=["dense", "scan", "pallas"])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_backward_matches_jax_grad(causal, inner):
    import jax
    import jax.numpy as jnp
    from veles.znicz_tpu import parallel
    from veles.znicz_tpu.parallel import ring

    mesh = parallel.make_mesh({"seq": 8})
    gen = prng.get("ringb")
    b, h, s, dh = 1, 2, 16, 4
    q = jnp.asarray(gen.normal(0, 1.0, (b, h, s, dh)))
    k = jnp.asarray(gen.normal(0, 1.0, (b, h, s, dh)))
    v = jnp.asarray(gen.normal(0, 1.0, (b, h, s, dh)))
    dout = jnp.asarray(gen.normal(0, 1.0, (b, h, s, dh)))

    # jit-wrapped like the production path (see the forward test):
    # the eager form cost ~40s/case in pure per-op SPMD dispatch
    out, lse = jax.jit(lambda a, b, c: ring.ring_self_attention(
        a, b, c, mesh, causal=causal, inner=inner, block=2))(q, k, v)
    dq, dk, dv = jax.jit(
        lambda a, b, c, o, l, d: ring.ring_self_attention_bwd(
            a, b, c, o, l, d, mesh, causal=causal, inner=inner,
            block=2))(q, k, v, out, lse, dout)

    def loss(q, k, v):
        return jnp.sum(jnp.asarray(dout)
                       * dense_attention(q, k, v, causal))

    gq, gk, gv = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for got, want, name in ((dq, gq, "dq"), (dk, gk, "dk"),
                            (dv, gv, "dv")):
        assert numpy.allclose(numpy.asarray(got), numpy.asarray(want),
                              atol=3e-4), \
            (name, numpy.abs(numpy.asarray(got)
                             - numpy.asarray(want)).max())


def test_mha_unit_ring_path_matches_dense():
    """The attention UNIT with seq_mesh set (forward + backward) equals
    its own dense path."""
    import jax
    from veles.znicz_tpu import parallel
    from veles.znicz_tpu.ops.attention import MultiHeadAttention
    from tests.test_conv_stack import build, xla_forward, xla_backward

    mesh = parallel.make_mesh({"seq": 8})
    wf, feed, fwd, gd, x, err, comp = build(
        MultiHeadAttention, input_shape=(2, 16, 8), gd_kwargs={},
        heads=2)
    params0 = comp.gather_params()
    state0 = comp.gather_state()
    y_dense = numpy.asarray(
        xla_forward(comp, feed, fwd, params0, x))
    ei_dense, params_dense = xla_backward(
        comp, feed, fwd, gd, params0, state0, x, err)

    fwd.seq_mesh = mesh
    y_ring = numpy.asarray(xla_forward(comp, feed, fwd, params0, x))
    ei_ring, params_ring = xla_backward(
        comp, feed, fwd, gd, params0, state0, x, err)
    fwd.seq_mesh = None

    assert numpy.allclose(y_ring, y_dense, atol=3e-5)
    assert numpy.allclose(numpy.asarray(ei_ring),
                          numpy.asarray(ei_dense), atol=3e-4)
    for pname in params_dense[fwd.name]:
        assert numpy.allclose(
            numpy.asarray(params_ring[fwd.name][pname]),
            numpy.asarray(params_dense[fwd.name][pname]),
            atol=3e-4), pname


def test_init_multihost_arg_plumbing(monkeypatch):
    """init_multihost has never run against a real pod (single-chip
    environment — see docs/PARALLELISM.md caveat); at minimum its
    argument plumbing into jax.distributed.initialize must be right,
    including the auto-detect (no-args) path."""
    import jax
    from veles.znicz_tpu import parallel

    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    monkeypatch.setattr(jax, "process_index", lambda: 3)
    monkeypatch.setattr(jax, "process_count", lambda: 8)

    rank, count = parallel.init_multihost("10.0.0.1:1234", 8, 3)
    assert calls[-1] == {"coordinator_address": "10.0.0.1:1234",
                         "num_processes": 8, "process_id": 3}
    assert (rank, count) == (3, 8)
    # cloud-TPU auto-detect: nothing passed through
    parallel.init_multihost()
    assert calls[-1] == {}
