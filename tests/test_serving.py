"""veles.serving: registry / engine / batcher / HTTP frontend, plus
the round-5 satellite regressions (GA slave error ack, WebDAV
absolute-URL snapshot listing, footprint-derived pallas VMEM grant).

The acceptance path (ISSUE 1): ``velescli.py serve`` answering a
concurrent-client predict load against an exported MNIST model with
dynamic batching — batch-fill ratio > 1 observed via ``/metrics.json``,
deadlines enforced, shedding instead of unbounded queueing — on the
numpy/CPU backend.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy
import pytest

import veles.prng as prng
from veles.config import root

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- shared trained artifact -------------------------------------------


@pytest.fixture(scope="module")
def mnist_artifact(tmp_path_factory):
    """Train a tiny MNIST MLP on numpy, snapshot + export it once."""
    prng.seed_all(4242)
    from veles.znicz_tpu.models import mnist
    saved_loader = {k: root.mnist.loader.get(k)
                    for k in ("minibatch_size", "n_train", "n_valid")}
    saved_epochs = root.mnist.decision.get("max_epochs")
    root.mnist.loader.update({"minibatch_size": 50, "n_train": 300,
                              "n_valid": 100})
    root.mnist.decision.max_epochs = 2
    base = tmp_path_factory.mktemp("serving")
    try:
        wf = mnist.StandardWorkflow(
            None, name="ServeTrain", layers=root.mnist.layers,
            loader_factory=lambda w: mnist.MnistLoader(
                w, name="loader", minibatch_size=50),
            decision_config=root.mnist.decision.to_dict(),
            snapshotter_config={"directory": str(base / "snapshots")})
        wf.initialize(device="numpy")
        wf.run()
        archive = str(base / "archive")
        wf.export_inference(archive)
        x = wf.loader.original_data.mem[:9].astype(numpy.float32)
        params = {
            "w1": wf.forwards[0].weights.map_read().mem.copy(),
            "b1": wf.forwards[0].bias.map_read().mem.copy(),
            "w2": wf.forwards[1].weights.map_read().mem.copy(),
            "b2": wf.forwards[1].bias.map_read().mem.copy(),
        }
        yield {"archive": archive, "x": x, "params": params,
               "unit_names": [u.name for u in wf.forwards],
               "snapshot": wf.snapshotter.destination}
    finally:
        root.mnist.loader.update(saved_loader)
        root.mnist.decision.max_epochs = saved_epochs


def mlp_oracle(p, x):
    h = 1.7159 * numpy.tanh((2.0 / 3.0) * (x @ p["w1"] + p["b1"]))
    v = h @ p["w2"] + p["b2"]
    e = numpy.exp(v - v.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


# -- registry + engine -------------------------------------------------


def test_registry_numpy_matches_training_forward(mnist_artifact):
    from veles.serving import ModelRegistry
    reg = ModelRegistry(backend="numpy")
    try:
        entry = reg.load("mnist", mnist_artifact["archive"])
        out = entry.predict(mnist_artifact["x"])
        expected = mlp_oracle(mnist_artifact["params"],
                              mnist_artifact["x"])
        numpy.testing.assert_allclose(out, expected, atol=1e-6)
        desc = entry.describe()
        assert desc["units"] == ["all2all_tanh", "softmax"]
        assert desc["input_sample_shape"] == (784,)
    finally:
        reg.close()


def test_jit_engine_bucket_cache(mnist_artifact):
    """The per-(model, bucket) compiled cache: warmup precompiles the
    power-of-two ladder, every batch size rides an existing bucket."""
    from veles.serving import ModelRegistry
    from veles.serving.engine import bucket_sizes
    reg = ModelRegistry(backend="jit", max_batch=16)
    try:
        entry = reg.load("mnist", mnist_artifact["archive"],
                         warmup=True)
        assert entry.engine.compiled_buckets == \
            bucket_sizes(16) == [1, 2, 4, 8, 16]
        expected = mlp_oracle(mnist_artifact["params"],
                              mnist_artifact["x"])
        for n in (1, 3, 9):
            out, bucket = entry.engine.predict(
                mnist_artifact["x"][:n])
            assert bucket == entry.engine.bucket_for(n)
            numpy.testing.assert_allclose(out, expected[:n],
                                          atol=1e-5)
        # no new compiles happened: every size mapped onto the ladder
        assert entry.engine.compiled_buckets == [1, 2, 4, 8, 16]
        with pytest.raises(ValueError, match="max_batch"):
            entry.engine.bucket_for(17)
    finally:
        reg.close()


def test_jit_engine_without_recorded_sample_shape(mnist_artifact):
    """Archives exported from loader-less workflows record
    input_sample_shape: null — the jit engine must still compile from
    the real request shape (review finding: it used to lower a rank-1
    spec and 500 every request)."""
    from veles.serving import ArchiveModel
    from veles.serving.engine import InferenceEngine
    model = ArchiveModel.from_dir(mnist_artifact["archive"])
    model.input_sample_shape = None
    engine = InferenceEngine(model, backend="jit", max_batch=8)
    assert engine.warmup() == {}      # nothing to precompile from
    out, bucket = engine.predict(mnist_artifact["x"][:3])
    assert bucket == 4
    numpy.testing.assert_allclose(
        out, mlp_oracle(mnist_artifact["params"],
                        mnist_artifact["x"][:3]), atol=1e-5)
    assert engine.compiled_buckets == [4]


def test_registry_checkpoint_refresh(mnist_artifact):
    """Params refresh from a snapshotter checkpoint (the best-epoch
    view), by unit name."""
    from veles.serving import ArchiveModel
    from veles.snapshotter import load_snapshot
    model = ArchiveModel.from_dir(mnist_artifact["archive"])
    loaded = model.load_checkpoint(mnist_artifact["snapshot"])
    assert loaded >= 4            # 2 x (weights, bias)
    state = load_snapshot(mnist_artifact["snapshot"])
    name0 = mnist_artifact["unit_names"][0]
    numpy.testing.assert_allclose(
        model.params[name0]["weights"],
        state["params"][name0]["weights"], atol=1e-6)


def test_hot_reload_bumps_version_and_keeps_cache(mnist_artifact,
                                                  tmp_path):
    """Same-architecture reload swaps params in place: version bumps,
    compiled programs survive, outputs track the new weights."""
    import shutil
    from veles.serving import ModelRegistry
    src = str(tmp_path / "archive")
    shutil.copytree(mnist_artifact["archive"], src)
    reg = ModelRegistry(backend="jit", max_batch=8)
    try:
        entry = reg.load("m", src, warmup=True)
        buckets = list(entry.engine.compiled_buckets)
        before = entry.predict(mnist_artifact["x"][:2])
        # retrain stand-in: zero the head weights on disk -> uniform
        with open(os.path.join(src, "contents.json")) as f:
            head = [u for u in json.load(f)["units"]
                    if u["type"] == "softmax"][0]
        for key in ("weights", "bias"):
            path = os.path.join(src, head[key])
            numpy.save(path, numpy.zeros_like(numpy.load(path)))
        entry2 = reg.reload("m")
        assert entry2 is entry and entry.version == 2
        assert entry.engine.compiled_buckets == buckets
        after = entry.predict(mnist_artifact["x"][:2])
        assert numpy.abs(after - before).max() > 1e-4
        numpy.testing.assert_allclose(after, 0.1, atol=1e-6)
    finally:
        reg.close()


def test_conv_model_serving_matches_numpy_units():
    """Coverage past the MLP: the conv/pooling interpreter ops equal
    the training units' numpy oracle on the CIFAR stack."""
    prng.seed_all(77)
    from veles.serving import ArchiveModel
    from veles.znicz_tpu.models import cifar10
    saved = {k: root.cifar.loader.get(k)
             for k in ("minibatch_size", "n_train", "n_valid")}
    root.cifar.loader.update({"minibatch_size": 10, "n_train": 40,
                              "n_valid": 20})
    try:
        wf = cifar10.create_workflow(name="ServeConv")
        wf.initialize(device="numpy")
        import tempfile
        with tempfile.TemporaryDirectory() as tmp:
            wf.export_inference(tmp)
            model = ArchiveModel.from_dir(tmp)
        wf.loader.run()
        x = wf.loader.minibatch_data.mem.astype(numpy.float32).copy()
        for u in wf.forwards:
            u.run()
        expected = wf.forwards[-1].output.mem
        numpy.testing.assert_allclose(model(x), expected, atol=1e-5)
    finally:
        root.cifar.loader.update(saved)


def test_moe_serving_is_per_request_deterministic(rng):
    """MoE routing/capacity must be a function of each sample alone:
    co-batched traffic (or bucket pad rows) must not change which
    tokens an expert drops (review finding)."""
    from veles.serving import ArchiveModel
    d, e, h, seq = 8, 4, 16, 6
    params = {"moe": {
        "router": rng.normal(0, 1, (d, e)).astype(numpy.float32),
        "weights": rng.normal(0, 0.3, (e, d, h)).astype(numpy.float32),
        "bias": numpy.zeros((e, h), numpy.float32),
        "weights2": rng.normal(0, 0.3, (e, h, d)).astype(numpy.float32),
        "bias2": numpy.zeros((e, d), numpy.float32),
    }}
    spec = {"type": "moe_ffn", "name": "moe",
            "config": {"experts": e, "hidden": h, "residual": True,
                       "capacity_factor": 1.0}}
    model = ArchiveModel("moe_wf", (seq, d), [spec], params)
    x = rng.normal(0, 1, (5, seq, d)).astype(numpy.float32)
    batched = model(x)
    for i in range(len(x)):
        numpy.testing.assert_allclose(
            model(x[i:i + 1])[0], batched[i], atol=1e-6,
            err_msg="row %d depends on co-batched rows" % i)


def test_batcher_groups_mixed_sample_shapes():
    """Differently-shaped requests (no-sample-shape archives) must not
    poison each other's batch (review finding)."""
    from veles.serving import MicroBatcher

    def echo(rows):
        time.sleep(0.005)
        return rows + 1.0, rows.shape[0]

    b = MicroBatcher(echo, max_batch=16, max_wait_ms=20.0)
    try:
        results = {}

        def client(i):
            shape = (1, 4) if i % 2 else (1, 6)
            results[i] = (shape,
                          b.predict(numpy.zeros(shape, numpy.float32)))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(10)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 10
        for shape, out in results.values():
            assert out.shape == shape
            numpy.testing.assert_array_equal(out, numpy.ones(shape))
    finally:
        b.close()


# -- batcher -----------------------------------------------------------


def test_batcher_coalesces_concurrent_requests():
    from veles.serving import MicroBatcher
    calls = []

    def run_batch(rows):
        calls.append(rows.shape[0])
        time.sleep(0.005)            # give the queue time to fill
        return rows * 2.0, rows.shape[0]

    b = MicroBatcher(run_batch, max_batch=16, max_wait_ms=20.0)
    try:
        results = {}

        def client(i):
            results[i] = b.predict(
                numpy.full((1, 4), float(i), numpy.float32))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 24
        for i, out in results.items():
            numpy.testing.assert_array_equal(out, numpy.full(
                (1, 4), 2.0 * i, numpy.float32))
        m = b.metrics()
        assert m["requests_total"] == 24
        assert m["batches_total"] == len(calls) < 24
        assert m["batch_fill_ratio"] > 1.0
        assert max(calls) <= 16
        assert m["latency_ms_p99"] >= m["latency_ms_p50"] > 0
    finally:
        b.close()


def test_batcher_enforces_deadlines():
    from veles.serving import DeadlineExceeded, MicroBatcher
    release = threading.Event()

    def slow_batch(rows):
        release.wait(timeout=5)
        return rows, rows.shape[0]

    b = MicroBatcher(slow_batch, max_batch=4, max_wait_ms=1.0)
    try:
        first = b.submit(numpy.zeros((1, 2), numpy.float32),
                         timeout_ms=5000)
        time.sleep(0.05)             # worker is now stuck in batch 1
        doomed = b.submit(numpy.zeros((1, 2), numpy.float32),
                          timeout_ms=10)
        time.sleep(0.05)
        release.set()
        first.event.wait(5)
        doomed.event.wait(5)
        assert first.error is None
        assert isinstance(doomed.error, DeadlineExceeded)
        assert b.metrics()["expired_total"] == 1
    finally:
        release.set()
        b.close()


def test_batcher_sheds_instead_of_queueing_unboundedly():
    from veles.serving import MicroBatcher, QueueFull
    release = threading.Event()

    def slow_batch(rows):
        release.wait(timeout=5)
        return rows, rows.shape[0]

    b = MicroBatcher(slow_batch, max_batch=2, max_queue=3,
                     max_wait_ms=1.0)
    try:
        held = [b.submit(numpy.zeros((1, 2), numpy.float32))
                for _ in range(3)]
        time.sleep(0.05)
        # worker holds <=2 rows; <=1 slot left of the 3-row queue
        with pytest.raises(QueueFull):
            for _ in range(4):
                held.append(b.submit(
                    numpy.zeros((1, 2), numpy.float32)))
        assert b.metrics()["shed_total"] >= 1
    finally:
        release.set()
        b.close()


# -- HTTP frontend -----------------------------------------------------


def _post(url, doc, timeout=15):
    req = urllib.request.Request(
        url, json.dumps(doc).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _get(url, timeout=15):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_http_predict_round_trip(mnist_artifact):
    """End-to-end on the numpy backend: concurrent clients coalesce
    (fill ratio > 1 in /metrics.json), predictions match the oracle."""
    from veles.serving import ModelRegistry
    from veles.serving.frontend import ServingFrontend
    reg = ModelRegistry(backend="numpy", max_wait_ms=15.0)
    front = None
    try:
        reg.load("mnist", mnist_artifact["archive"])
        front = ServingFrontend(reg, port=0)
        base = "http://127.0.0.1:%d" % front.port
        assert _get(base + "/healthz")["status"] == "ok"
        # a loaded warm model on a closed-breaker registry is READY
        assert _get(base + "/readyz")["ready"] is True
        models = _get(base + "/v1/models")["models"]
        assert [m["name"] for m in models] == ["mnist"]

        x = mnist_artifact["x"]
        expected = mlp_oracle(mnist_artifact["params"], x)
        results = {}

        def client(i):
            results[i] = _post(base + "/v1/predict", {
                "model": "mnist",
                "inputs": [x[i % len(x)].tolist()]})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 24
        for i, doc in results.items():
            numpy.testing.assert_allclose(
                numpy.asarray(doc["outputs"][0]),
                expected[i % len(x)], atol=1e-5)
        m = _get(base + "/metrics.json")["models"]["mnist"]
        assert m["requests_total"] >= 24
        assert m["batch_fill_ratio"] > 1.0
        assert m["shed_total"] == 0
        assert m["latency_ms_p99"] > 0
        assert m["requests_per_sec"] > 0
    finally:
        if front is not None:
            front.close()
        reg.close()


def test_http_error_paths(mnist_artifact):
    from veles.serving import ModelRegistry
    from veles.serving.frontend import ServingFrontend
    reg = ModelRegistry(backend="numpy")
    front = None
    try:
        reg.load("mnist", mnist_artifact["archive"])
        front = ServingFrontend(reg, port=0)
        # exercised through the shared request handler (no sockets)
        code, _ = front.predict_request({"model": "nope",
                                         "inputs": [[0.0]]})
        assert code == 404
        code, _ = front.predict_request({"inputs": [[0.0]]})
        assert code == 400
        code, reply = front.predict_request(
            {"model": "mnist", "inputs": [[1.0, 2.0]]})
        assert code == 400 and "shape" in reply["error"]
        # single un-batched sample is promoted
        code, reply = front.predict_request(
            {"model": "mnist",
             "inputs": mnist_artifact["x"][0].tolist()})
        assert code == 200 and len(reply["outputs"]) == 1
        # oversized request is the CLIENT's fault -> 400, not 500
        big = numpy.zeros((reg.max_batch + 1, 784), numpy.float32)
        code, reply = front.predict_request(
            {"model": "mnist", "inputs": big.tolist()})
        assert code == 400 and "outside" in reply["error"]
        base = "http://127.0.0.1:%d" % front.port
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(base + "/nope")
        assert err.value.code == 404
    finally:
        if front is not None:
            front.close()
        reg.close()


def test_traceparent_propagation_and_debug_endpoints(mnist_artifact):
    """ISSUE 6 serving leg: a predict carrying a W3C traceparent gets
    the SAME trace echoed on the response, its queue wait and the
    batched execution appear as spans of that trace in the flight
    recorder, and the frontend serves /debug/trace + /debug/events."""
    from veles import telemetry
    from veles.serving import ModelRegistry
    from veles.serving.frontend import ServingFrontend
    reg = ModelRegistry(backend="numpy", max_wait_ms=1.0)
    front = None
    try:
        reg.load("mnist", mnist_artifact["archive"])
        front = ServingFrontend(reg, port=0)
        base = "http://127.0.0.1:%d" % front.port
        ctx = telemetry.TraceContext.new()
        req = urllib.request.Request(
            base + "/v1/predict",
            json.dumps({"model": "mnist",
                        "inputs": [mnist_artifact["x"][0].tolist()]}
                       ).encode(),
            method="POST",
            headers={"Content-Type": "application/json",
                     "traceparent": ctx.to_traceparent()})
        with urllib.request.urlopen(req, timeout=15) as resp:
            assert resp.status == 200
            echoed = resp.headers.get("traceparent")
        assert echoed == ctx.to_traceparent()

        # a request WITHOUT the header mints a fresh context
        req2 = urllib.request.Request(
            base + "/v1/predict",
            json.dumps({"model": "mnist",
                        "inputs": [mnist_artifact["x"][1].tolist()]}
                       ).encode(),
            method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req2, timeout=15) as resp:
            minted = resp.headers.get("traceparent")
        assert minted and minted != echoed
        from veles.telemetry import TraceContext
        assert TraceContext.from_traceparent(minted) is not None

        # flight recorder (never telemetry.tracer.start()ed) holds
        # the request's spans under ITS trace_id
        doc = _get(base + "/debug/trace")
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        mine = [e for e in spans
                if e.get("args", {}).get("trace_id") == ctx.trace_id]
        names = {e["name"] for e in mine}
        assert "http.predict" in names, sorted(names)
        assert "serving.queue" in names, sorted(names)
        assert any(e["name"] == "serving.execute" for e in spans)
        events_doc = _get(base + "/debug/events")
        assert "events" in events_doc
    finally:
        if front is not None:
            front.close()
        reg.close()


def test_web_status_surfaces_serving_metrics(mnist_artifact):
    from veles.serving import ModelRegistry
    from veles.serving.frontend import ServingFrontend
    from veles.web_status import WebStatus
    reg = ModelRegistry(backend="numpy")
    front = status = None
    try:
        reg.load("mnist", mnist_artifact["archive"])
        front = ServingFrontend(reg, port=0)
        status = WebStatus(port=0)
        front.register_status(status)
        reg.get("mnist").predict(mnist_artifact["x"][:1])
        snap = status.snapshot()
        entry = snap["serving:%d" % front.port]
        assert entry["mode"] == "serving"
        assert entry["workflow"] == "mnist"
        assert entry["last_metrics"]["mnist"]["rps"] >= 0
        assert "serving" in status.render_page()
    finally:
        if status is not None:
            status.close()
        if front is not None:
            front.close()
        reg.close()


def test_velescli_serve_subcommand(mnist_artifact):
    """The acceptance path: ``velescli.py serve`` under concurrent
    HTTP load — dynamic batching visible in /metrics.json."""
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "velescli.py"), "serve",
         "--model", "mnist=%s" % mnist_artifact["archive"],
         "--port", "0", "--backend", "numpy",
         "--max-wait-ms", "15", "--timeout-ms", "5000"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), text=True)
    try:
        line = proc.stdout.readline()
        base = json.loads(line)["serving"]
        x = mnist_artifact["x"]
        expected = mlp_oracle(mnist_artifact["params"], x)
        results = {}

        def client(i):
            results[i] = _post(base + "/v1/predict", {
                "model": "mnist",
                "inputs": [x[i % len(x)].tolist()],
                "timeout_ms": 5000})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, doc in results.items():
            numpy.testing.assert_allclose(
                numpy.asarray(doc["outputs"][0]),
                expected[i % len(x)], atol=1e-5)
        m = _get(base + "/metrics.json")["models"]["mnist"]
        assert m["requests_total"] >= 16
        assert m["batch_fill_ratio"] > 1.0
        assert m["expired_total"] == 0
    finally:
        proc.terminate()
        proc.wait(timeout=10)


# -- satellite regressions (ADVICE round 5) ----------------------------


def _ga_eval(values):          # module-level: ships through pickle
    return 0.25


def test_ga_slave_stops_on_result_error_reply():
    """A master ('error', ...) reply to a result frame must NOT count
    as served (the slave used to treat any reply as an ack)."""
    import socket
    from veles.genetics import ga_slave_loop
    from veles.server import recv_frame, send_frame
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    seen = []

    def master():
        conn, _ = srv.accept()
        try:
            while True:
                frame = recv_frame(conn)
                if frame is None:
                    return
                seen.append(frame[0])
                if frame[0] == "hello":
                    send_frame(conn, ("welcome", 7))
                elif frame[0] == "task":
                    send_frame(conn, ("task", 0, _ga_eval,
                                      {"lr": 0.1}, 0))
                elif frame[0] == "result":
                    send_frame(conn, ("error", "mixed-build master "
                                      "refused the frame"))
        finally:
            conn.close()

    t = threading.Thread(target=master, daemon=True)
    t.start()
    try:
        served = ga_slave_loop("127.0.0.1:%d" % port, name="t-slave",
                               max_tasks=5, reconnect_attempts=1,
                               reconnect_delay=0.01)
    finally:
        srv.close()
        t.join(timeout=5)
    assert served == 0
    assert "result" in seen        # the evaluation WAS reported


def test_http_snapshot_store_lists_absolute_url_hrefs(caplog):
    """WebDAV-style listers returning FULL URLs must still resolve to
    base-relative names; an all-filtered listing must be logged."""
    import logging
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from veles.snapshotter import HTTPSnapshotStore
    payload = {"doc": None}

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def do_GET(self):
            body = json.dumps(payload["doc"]).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        base = "http://127.0.0.1:%d/bucket" % httpd.server_address[1]
        store = HTTPSnapshotStore(base)
        payload["doc"] = [
            base + "/wf_=0.01.ckpt.npz.gz",        # absolute URL
            "/bucket/wf_=0.02.ckpt.npz.gz",        # absolute path
            "wf_=0.03.ckpt.npz.gz",                # relative
            base + "/other/foreign_=9.ckpt.npz.gz",  # foreign prefix
            "readme.txt",                          # not a checkpoint
        ]
        assert store.list() == ["wf_=0.01.ckpt.npz.gz",
                                "wf_=0.02.ckpt.npz.gz",
                                "wf_=0.03.ckpt.npz.gz"]
        payload["doc"] = ["http://elsewhere/x/a.ckpt.npz.gz",
                          "junk.bin"]
        with caplog.at_level(logging.WARNING):
            assert store.list() == []
        assert any("filtered out" in r.getMessage()
                   for r in caplog.records)
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_fused_bwd_vmem_limit_tracks_footprint():
    """The pallas fused-backward VMEM grant derives from the resident
    footprint, clamps to the device generation, and names
    ``fused=False`` as the escape hatch when nothing fits."""
    from veles.znicz_tpu.parallel.pallas_attention import (
        _fused_bwd_vmem_limit)
    # small shapes keep the default 16MB floor
    small = _fused_bwd_vmem_limit(512, 64, 128, 128, 2,
                                  device_vmem=128 << 20)
    assert small == 16 << 20
    # the measured S=8k case: grant covers the observed 16.75MB need
    # without claiming the whole chip
    grant = _fused_bwd_vmem_limit(8192, 64, 128, 128, 2,
                                  device_vmem=128 << 20)
    assert (17 << 20) < grant < (64 << 20)
    # monotone in S, never past the device capacity
    bigger = _fused_bwd_vmem_limit(16384, 64, 128, 128, 2,
                                   device_vmem=128 << 20)
    assert grant < bigger <= 128 << 20
    # a v2/v3-sized VMEM refuses the fused path LOUDLY, pointing at
    # the two-kernel fallback
    with pytest.raises(ValueError, match="fused=False"):
        _fused_bwd_vmem_limit(8192, 64, 128, 128, 2,
                              device_vmem=16 << 20)


def test_bench_serving_row_runs():
    """bench.py's serving_throughput_rps: in-process, no sockets, no
    device required."""
    import bench
    rps, fill, cache = bench.serving_throughput_rps(duration=0.3,
                                                    clients=4)
    assert cache > 0
    assert rps > 0
    assert fill >= 1.0
