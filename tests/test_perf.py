"""Per-step performance accounting (ISSUE 6, veles/perf.py): the
jaxpr cost walker's arithmetic against hand-counted FLOPs, scan
trip-count multiplication (the case XLA's own HLO analysis gets
wrong), ledger caching/degradation, and the ``veles_step_*`` metric
families on a real compiled-step run."""

import os

import numpy
import pytest

from veles import perf, telemetry


def test_matmul_flops_exact():
    import jax
    import jax.numpy as jnp
    f = jax.jit(lambda x: x @ x)
    cost = perf.program_cost(f, (jnp.ones((8, 8)),))
    # 2*M*N*K multiply-adds, nothing else in the program
    assert cost.flops == 2 * 8 * 8 * 8
    assert cost.bytes > 0 and cost.io_bytes == 2 * 8 * 8 * 4


def test_scan_multiplies_trip_count():
    import jax
    import jax.numpy as jnp

    def step(c, x):
        return c @ x, jnp.sum(c)

    f = jax.jit(lambda c, xs: jax.lax.scan(step, c, xs))
    args = (jnp.ones((8, 8)), jnp.ones((10, 8, 8)))
    cost = perf.program_cost(f, args)
    # 10 iterations of a 1024-flop matmul (+ the per-step reduce);
    # the XLA HLO analysis of the same program counts the while body
    # ONCE — the whole reason the walker exists
    assert cost.flops >= 10 * 1024
    assert cost.flops < 20 * 1024
    lowered_flops = f.lower(*args).cost_analysis().get("flops", 0)
    assert lowered_flops < 10 * 1024  # documents the gap we close


def test_conv_flops_counts_kernel_footprint():
    import jax.numpy as jnp
    from jax import lax

    def conv(x, k):
        return lax.conv_general_dilated(x, k, (1, 1), "VALID")

    cost = perf.program_cost(
        conv, (jnp.ones((1, 3, 8, 8)), jnp.ones((4, 3, 3, 3))))
    # out (1,4,6,6); per output: 3*3*3 kernel taps, 2 flops each
    assert cost.flops == 2 * (1 * 4 * 6 * 6) * (3 * 3 * 3)


def test_ledger_caches_and_degrades():
    import jax.numpy as jnp
    ledger = perf.PerfLedger()
    calls = []

    def f(x):
        calls.append(1)
        return x * 2

    args = (jnp.ones((4,)),)
    c1 = ledger.cost(("k", 1), f, args)
    c2 = ledger.cost(("k", 1), f, args)
    assert c1 is c2 and len(calls) == 1   # analyzed once
    # an unanalyzable program degrades to zero cost, never raises
    bad = ledger.cost(("k", 2), lambda: 1 / 0, ())
    assert bad.flops == 0.0
    # recording with a zero cost and no samples is a no-op, not a crash
    ledger.record_dispatch("step", bad, 0.01)


def test_device_peak_env_override(monkeypatch):
    monkeypatch.setenv("VELES_PEAK_FLOPS", "2.5e12")
    assert perf.device_peak_flops() == 2.5e12
    monkeypatch.setenv("VELES_PEAK_FLOPS", "garbage")
    # garbage falls through to device detection (cpu -> None)
    assert perf.device_peak_flops() is None


def test_device_peak_low_precision_overrides(monkeypatch):
    """Per-precision env escape hatches: an int8/fp8 program's MFU
    must not silently score against the bf16 peak (ISSUE 14
    satellite)."""
    monkeypatch.setenv("VELES_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("VELES_PEAK_FLOPS_INT8", "2e12")
    monkeypatch.setenv("VELES_PEAK_FLOPS_FP8", "3e12")
    assert perf.device_peak_flops("bf16") == 1e12
    assert perf.device_peak_flops("int8") == 2e12
    assert perf.device_peak_flops("fp8") == 3e12
    # without the per-precision env, int8 on an unknown device (cpu)
    # stays unknown rather than borrowing the bf16 override
    monkeypatch.delenv("VELES_PEAK_FLOPS_INT8")
    assert perf.device_peak_flops("int8") is None


def test_program_precision_detection():
    """The cost walker classifies a program by its dominant dot-input
    class: plain f32/bf16 -> "bf16", an int8-dominated matmul program
    -> "int8", float8 -> "fp8"."""
    import jax
    import jax.numpy as jnp
    f32 = perf.program_cost(
        jax.jit(lambda x: x @ x), (jnp.ones((8, 8)),))
    assert f32.precision == "bf16"

    def int8_dot(q, x):
        return jax.lax.dot_general(
            x, q.astype(jnp.float32) * 0.5, (((1,), (0,)), ((), ())))

    # the dequant-convert keeps the dot inputs f32 — that program is
    # NOT int8-classed (inputs decide, matching what the MXU runs)
    cost = perf.program_cost(
        int8_dot, (jnp.ones((8, 8), jnp.int8), jnp.ones((4, 8))))
    assert cost.precision == "bf16"

    def raw_int8(q, x):
        return jax.lax.dot_general(
            q, x, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)

    cost = perf.program_cost(
        raw_int8, (jnp.ones((8, 8), jnp.int8),
                   jnp.ones((8, 8), jnp.int8)))
    assert cost.precision == "int8"

    def fp8_dot(a, b):
        return jax.lax.dot_general(
            a, b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    cost = perf.program_cost(
        fp8_dot, (jnp.ones((8, 8), jnp.float8_e4m3fn),
                  jnp.ones((8, 8), jnp.float8_e4m3fn)))
    assert cost.precision == "fp8"

    # mixed operands run the WIDE rate (the hardware upcasts): an
    # int8-lhs × bf16-rhs dot must not be scored against the doubled
    # 8-bit peak
    def mixed_dot(q, x):
        return jax.lax.dot_general(
            q, x, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    cost = perf.program_cost(
        mixed_dot, (jnp.ones((8, 8), jnp.int8),
                    jnp.ones((8, 8), jnp.bfloat16)))
    assert cost.precision == "bf16"


def test_step_metrics_on_real_run(monkeypatch):
    """Acceptance slice: after an XLA-backed training run, /metrics
    exports non-zero veles_step_flops_total and bytes, achieved
    FLOP/s, samples/s and — with a known peak — an MFU ratio."""
    monkeypatch.setenv("VELES_PEAK_FLOPS", "1e12")
    import veles.prng as prng
    from veles.config import root
    from veles.znicz_tpu.models import mnist
    prng.seed_all(406)
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    saved_epochs = root.mnist.decision.get("max_epochs")
    root.mnist.loader.update(
        {"n_train": 64, "n_valid": 32, "minibatch_size": 16})
    root.mnist.decision.max_epochs = 2
    try:
        wf = mnist.create_workflow(name="PerfRun")
        wf.initialize(device="cpu")
        wf.run()
    finally:
        root.mnist.loader.update(saved)
        root.mnist.decision.max_epochs = saved_epochs
    reg = telemetry.get_registry()
    flops = reg.counter_total("veles_step_flops_total")
    assert flops > 0
    assert reg.counter_total("veles_step_bytes_total") > 0
    text = reg.render_prometheus()
    assert "veles_step_flops_per_second" in text
    assert "veles_step_samples_per_second" in text
    assert "veles_step_mfu_ratio" in text
    # the flop count is plausible for the MLP: 2 epochs x 96 samples
    # through a 784->100->10 net, fwd+bwd — within two orders of the
    # hand count (the walker includes elementwise estimates)
    hand = 2 * 96 * 2 * (784 * 100 + 100 * 10) * 3
    assert hand / 100 < flops < hand * 100, (flops, hand)


def test_tokens_per_second_for_lm_loaders():
    """An LM loader's (mb, S) integer minibatch yields a tokens/s
    gauge; float image batches must not."""

    class FakeMem:
        def __init__(self, arr):
            self.mem = arr

    class Step:
        _tokens_per_sample = None

    from veles.znicz_tpu.xla_step import XLAStep
    step = XLAStep.__new__(XLAStep)
    step.loader = type("L", (), {})()
    step.loader.minibatch_data = FakeMem(
        numpy.zeros((4, 32), numpy.int32))
    assert XLAStep._tokens_per_sample(step) == 32
    step.loader.minibatch_data = FakeMem(
        numpy.zeros((4, 784), numpy.float32))
    assert XLAStep._tokens_per_sample(step) is None


def test_wire_bytes_counted_per_frame():
    """veles_wire_bytes_total accounts every frame both ways."""
    import socket
    import threading
    from veles.server import recv_frame, send_frame
    a, b = socket.socketpair()
    try:
        reg = telemetry.get_registry()
        payload = ("job", {"x": numpy.zeros(64).tolist()}, 1, 0)
        got = []
        t = threading.Thread(target=lambda: got.append(recv_frame(b)))
        t.start()
        send_frame(a, payload)
        t.join(timeout=10)
        assert got and got[0] == payload
        tx = reg.counter_total("veles_wire_bytes_total",
                               direction="tx")
        rx = reg.counter_total("veles_wire_bytes_total",
                               direction="rx")
        assert tx == rx and tx > 36     # header+tag+payload
    finally:
        a.close()
        b.close()
