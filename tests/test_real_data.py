"""Real-dataset auto-ingest (VERDICT r2 item 4): staging idx/binary
files under root.common.dirs.datasets switches every loader off the
synthetic stand-ins with ZERO code changes; provenance records the
source + validation level so bench numbers stay labelled."""

import gzip
import struct

import numpy
import pytest

import veles.prng as prng
from veles.config import root
from veles.znicz_tpu.models import datasets


@pytest.fixture()
def staged_datasets(tmp_path, monkeypatch):
    """A tiny-but-structurally-valid MNIST idx + CIFAR-10 binary tree."""
    monkeypatch.setattr(root.common.dirs, "datasets", str(tmp_path))
    gen = numpy.random.Generator(numpy.random.PCG64(99))
    mnist = tmp_path / "MNIST"
    mnist.mkdir()

    def write_idx(path, arr):
        ndim = arr.ndim
        head = struct.pack(">i", 0x0800 + ndim)
        head += struct.pack(">" + "i" * ndim, *arr.shape)
        opener = gzip.open if str(path).endswith(".gz") else open
        with opener(path, "wb") as f:
            f.write(head + arr.astype(numpy.uint8).tobytes())

    timg = gen.integers(0, 255, (64, 28, 28), dtype=numpy.uint8)
    tlab = (numpy.arange(64) % 10).astype(numpy.uint8)
    vimg = gen.integers(0, 255, (32, 28, 28), dtype=numpy.uint8)
    vlab = (numpy.arange(32) % 10).astype(numpy.uint8)
    write_idx(mnist / "train-images-idx3-ubyte", timg)
    write_idx(mnist / "train-labels-idx1-ubyte", tlab)
    # mixed compression: the gz path must work too
    write_idx(mnist / "t10k-images-idx3-ubyte.gz", vimg)
    write_idx(mnist / "t10k-labels-idx1-ubyte.gz", vlab)

    cifar = tmp_path / "cifar-10-batches-bin"
    cifar.mkdir()
    for name, n in [("data_batch_%d.bin" % i, 20) for i in
                    range(1, 6)] + [("test_batch.bin", 10)]:
        rec = numpy.zeros((n, 3073), numpy.uint8)
        rec[:, 0] = numpy.arange(n) % 10
        rec[:, 1:] = gen.integers(0, 255, (n, 3072), dtype=numpy.uint8)
        (cifar / name).write_bytes(rec.tobytes())
    return {"mnist_train_images": timg, "cifar_n_train": 100}


def test_mnist_prefers_staged_real_data(staged_datasets):
    tx, ty, vx, vy = datasets.load_mnist(n_train=50, n_valid=20)
    prov = datasets.data_provenance("mnist")
    assert prov["source"] == "real"
    assert "NON-CANONICAL" in prov["checksum"]  # fixture != real MNIST
    assert tx.shape == (50, 28, 28)
    # the actual staged bytes, not synthetic ones
    want = staged_datasets["mnist_train_images"][:50] / 255.0
    assert numpy.allclose(tx, want)
    assert vy.shape == (20,) and vy.max() <= 9


def test_cifar_prefers_staged_real_data(staged_datasets):
    tx, ty, vx, vy = datasets.load_cifar10()
    prov = datasets.data_provenance("cifar10")
    assert prov["source"] == "real"
    assert tx.shape == (staged_datasets["cifar_n_train"], 3, 32, 32)
    assert vx.shape[0] == 10


def test_corrupt_staged_data_falls_back(tmp_path, monkeypatch):
    """A present-but-invalid file must not poison training: loud
    fallback to synthetic."""
    monkeypatch.setattr(root.common.dirs, "datasets", str(tmp_path))
    mnist = tmp_path / "MNIST"
    mnist.mkdir()
    (mnist / "train-images-idx3-ubyte").write_bytes(b"garbage-bytes")
    tx, ty, vx, vy = datasets.load_mnist(n_train=30, n_valid=10)
    assert datasets.data_provenance("mnist")["source"] == "synthetic"
    assert tx.shape == (30, 28 * 28) or tx.shape == (30, 28, 28)


def test_workflow_trains_on_staged_real_data(staged_datasets):
    """The whole point: the SAME workflow code trains on the staged
    real tree, no config or code changes."""
    prng.seed_all(11)
    from veles.znicz_tpu.models import mnist
    saved = root.mnist.loader.to_dict()
    root.mnist.loader.update({"n_train": 60, "n_valid": 20,
                              "minibatch_size": 20})
    root.mnist.decision.max_epochs = 2
    try:
        wf = mnist.create_workflow(name="RealDataMnist")
        wf.initialize(device="cpu")
        wf.run()
    finally:
        root.mnist.loader.update(saved)
    assert datasets.data_provenance("mnist")["source"] == "real"
    assert wf.end_point.reached
    assert len(wf.decision.history) == 2


def test_bench_json_carries_data_tag(staged_datasets):
    """bench.py labels which data fed each number."""
    datasets.load_mnist(n_train=30, n_valid=10)
    tags = {k: v.get("source")
            for k, v in datasets.data_provenance().items()}
    assert tags.get("mnist") == "real"


def _png_bytes(gen):
    """A tiny valid PNG (the loaders only need decodable files)."""
    import io
    from PIL import Image
    img = Image.fromarray(
        gen.integers(0, 255, (8, 8, 3), dtype=numpy.uint8))
    buf = io.BytesIO()
    img.save(buf, "PNG")
    return buf.getvalue()


def test_imagenet_prep_stages_ilsvrc_archives(tmp_path, monkeypatch):
    """imagenet_prep turns raw-ILSVRC-shaped archives (train tar of
    per-class tars; flat val tar + ground truth + synsets) into the
    class tree models/imagenet.py auto-ingests."""
    import io
    import tarfile
    from veles.znicz_tpu.models import imagenet_prep

    gen = numpy.random.Generator(numpy.random.PCG64(1))
    wnids = ["n01440764", "n01443537", "n01484850"]

    def add_bytes(tar, name, payload):
        info = tarfile.TarInfo(name)
        info.size = len(payload)
        tar.addfile(info, io.BytesIO(payload))

    # train: outer tar of per-class tars, 2 images each
    train_tar = tmp_path / "train.tar"
    with tarfile.open(train_tar, "w") as outer:
        for wnid in wnids:
            inner_buf = io.BytesIO()
            with tarfile.open(fileobj=inner_buf, mode="w") as inner:
                for i in range(2):
                    add_bytes(inner, "%s_%d.JPEG" % (wnid, i),
                              _png_bytes(gen))
            add_bytes(outer, wnid + ".tar", inner_buf.getvalue())
    # val: flat tar + 1-based ids in sorted-filename order + synsets
    val_tar = tmp_path / "val.tar"
    with tarfile.open(val_tar, "w") as tar:
        for i in range(4):
            add_bytes(tar, "ILSVRC2012_val_%08d.JPEG" % (i + 1),
                      _png_bytes(gen))
    labels = tmp_path / "gt.txt"
    labels.write_text("1\n3\n2\n1\n")
    # devkit ILSVRC2012_ID ordering is NOT alphabetical-by-wnid;
    # the fixture mirrors that (and stage_val rejects sorted lists)
    devkit_order = [wnids[1], wnids[0], wnids[2]]
    synsets = tmp_path / "synsets.txt"
    synsets.write_text("".join("%s desc %d\n" % (w, i)
                               for i, w in enumerate(devkit_order)))

    out = tmp_path / "datasets" / "ImageNet"
    n = imagenet_prep.stage_train(str(train_tar), str(out),
                                  log=lambda *a: None)
    assert n == 3
    # resume: second run stages nothing new
    assert imagenet_prep.stage_train(str(train_tar), str(out),
                                     log=lambda *a: None) == 0
    # a PARTIAL class (interrupted extraction) must be re-staged, not
    # skipped as complete
    import shutil
    shutil.move(str(out / "n01440764"),
                str(out / "n01440764.partial"))
    (out / "n01440764.partial" / "n01440764_1.JPEG").unlink()
    assert imagenet_prep.stage_train(str(train_tar), str(out),
                                     log=lambda *a: None) == 1
    assert len(list((out / "n01440764").iterdir())) == 2
    # validation stages into a SEPARATE tree: official val images must
    # not leak into the training split the loader carves from --out
    val_out = tmp_path / "datasets" / "ImageNet-val"
    # an alphabetically-sorted synset list is the signature of the
    # wnid-sorted synset_words.txt, whose line order does NOT match
    # the devkit ids the ground truth indexes — refuse it loudly
    sorted_synsets = tmp_path / "synsets_sorted.txt"
    sorted_synsets.write_text("".join("%s desc\n" % w for w in wnids))
    with pytest.raises(ValueError, match="alphabetical order"):
        imagenet_prep.stage_val(str(val_tar), str(labels),
                                str(sorted_synsets), str(val_out),
                                log=lambda *a: None)
    staged = imagenet_prep.stage_val(str(val_tar), str(labels),
                                     str(synsets), str(val_out),
                                     log=lambda *a: None)
    assert staged == 4
    # ids resolve through the DEVKIT order: id 1 -> devkit_order[0]
    assert len(list((val_out / devkit_order[0]).iterdir())) == 2
    assert len(list((val_out / devkit_order[2]).iterdir())) == 1
    assert len(list((val_out / devkit_order[1]).iterdir())) == 1
    for wnid, count in [("n01440764", 2), ("n01443537", 2),
                        ("n01484850", 2)]:
        assert len(list((out / wnid).iterdir())) == count
    assert sum(len(list(d.iterdir()))
               for d in val_out.iterdir()) == 4

    # the staged tree is exactly what models/imagenet.py auto-ingests
    monkeypatch.setattr(root.common.dirs, "datasets",
                        str(tmp_path / "datasets"))
    from veles.znicz_tpu.models import imagenet
    base, classes = imagenet._real_tree()
    assert base == str(out)
    assert classes == 3


def test_imagenet_prep_rejects_mismatched_ground_truth(tmp_path):
    import io
    import tarfile
    from veles.znicz_tpu.models import imagenet_prep
    gen = numpy.random.Generator(numpy.random.PCG64(2))
    val_tar = tmp_path / "val.tar"
    with tarfile.open(val_tar, "w") as tar:
        payload = _png_bytes(gen)
        info = tarfile.TarInfo("ILSVRC2012_val_00000001.JPEG")
        info.size = len(payload)
        tar.addfile(info, io.BytesIO(payload))
    (tmp_path / "gt.txt").write_text("1\n2\n")     # 2 labels, 1 image
    (tmp_path / "synsets.txt").write_text("n01440764 fish\n")
    with pytest.raises(ValueError, match="1 images but"):
        imagenet_prep.stage_val(
            str(val_tar), str(tmp_path / "gt.txt"),
            str(tmp_path / "synsets.txt"), str(tmp_path / "out"),
            log=lambda *a: None)
