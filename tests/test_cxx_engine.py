"""C++ inference engine integration (SURVEY.md §2.6, §3.5): a
Python-trained workflow exports to the archive format, the CMake engine
builds, and its forward pass matches the numpy oracle.

The build is cached in /tmp across test runs (ninja no-ops when
nothing changed)."""

import os
import shutil
import subprocess

import numpy
import pytest

import veles.prng as prng
from veles.config import root

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUILD_DIR = "/tmp/libveles-build-test"

#: environmental gate (ISSUE 13 satellite): without the build tools
#: every test here used to ERROR in the engine fixture on each tier-1
#: run — an honest skip says "cannot build here", not "code broke"
_missing = [tool for tool in ("cmake", "ninja")
            if shutil.which(tool) is None]
pytestmark = pytest.mark.skipif(
    bool(_missing),
    reason="C++ engine build unavailable: %s not installed "
           "(environmental)" % ", ".join(_missing))


@pytest.fixture(scope="module")
def engine():
    src = os.path.join(REPO, "libveles")
    subprocess.run(
        ["cmake", "-S", src, "-B", BUILD_DIR, "-G", "Ninja"],
        check=True, capture_output=True)
    subprocess.run(["cmake", "--build", BUILD_DIR],
                   check=True, capture_output=True)
    return BUILD_DIR


def _train_mnist(tmp_path):
    prng.seed_all(55)
    from veles.znicz_tpu.models import mnist
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    saved_epochs = root.mnist.decision.get("max_epochs")
    root.mnist.loader.update(
        {"n_train": 300, "n_valid": 100, "minibatch_size": 50})
    root.mnist.decision.max_epochs = 2
    try:
        wf = mnist.create_workflow(name="CxxExport")
        wf.initialize(device="numpy")
        wf.run()
    finally:
        root.mnist.loader.update(saved)
        root.mnist.decision.max_epochs = saved_epochs
    return wf


def _forward_oracle(wf, x):
    """Run the trained forward chain on a batch via the numpy path."""
    wf.loader.minibatch_data.map_invalidate()
    wf.loader.minibatch_data.mem[...] = x
    for f in wf.forwards:
        f.numpy_run()
    return numpy.array(wf.forwards[-1].output.map_read().mem)


def _run_infer(engine_dir, archive, x, tmp_path):
    inp = os.path.join(tmp_path, "input.npy")
    outp = os.path.join(tmp_path, "output.npy")
    numpy.save(inp, x.astype(numpy.float32))
    subprocess.run(
        [os.path.join(engine_dir, "veles_infer"), archive, inp, outp],
        check=True, capture_output=True)
    return numpy.load(outp)


def test_engine_selftest(engine):
    subprocess.run([os.path.join(engine, "test_engine")],
                   check=True, capture_output=True)


def test_mnist_mlp_matches_oracle(engine, tmp_path):
    wf = _train_mnist(tmp_path)
    archive = os.path.join(tmp_path, "archive")
    wf.export_inference(archive)
    x = numpy.array(wf.loader.minibatch_data.map_read().mem,
                    numpy.float32)
    expected = _forward_oracle(wf, x)
    got = _run_infer(engine, archive, x, str(tmp_path))
    assert got.shape == expected.shape
    numpy.testing.assert_allclose(got, expected, atol=1e-5)


def test_conv_net_matches_oracle(engine, tmp_path):
    """Conv + pooling + LRN + dropout + dense through the C++ path."""
    prng.seed_all(77)
    from veles.units import Unit
    from veles.workflow import Workflow
    from veles.znicz_tpu.nn_units import forward_by_name

    class Holder(Workflow):
        pass

    wf = Holder(None, name="CxxConv")
    b, h, w, c = 4, 12, 12, 3
    x = numpy.random.default_rng(5).normal(
        0, 1, (b, h, w, c)).astype(numpy.float32)

    class Src(Unit):
        def run(self):
            pass
    src = Src(wf, name="src")
    src.minibatch_data = None

    from veles.memory import Array
    data = Array()
    data.reset(x.copy())
    src.minibatch_data = data

    specs = [
        ("conv_relu", {"n_kernels": 5, "kx": 3, "ky": 3,
                       "padding": 1, "sliding": (1, 1)}),
        ("max_pooling", {"kx": 2, "ky": 2}),
        ("norm", {}),
        ("dropout", {"dropout_ratio": 0.3}),
        ("avg_pooling", {"kx": 2, "ky": 2}),
        ("softmax", {"output_sample_shape": 7}),
    ]
    forwards = []
    prev, attr = src, "minibatch_data"
    for kind, kwargs in specs:
        u = forward_by_name(kind)(wf, **kwargs)
        u.link_attrs(prev, ("input", attr))
        if kind == "dropout":
            # inference comparison: eval mode on both sides (without a
            # loader the oracle would default to the train phase)
            u.forward_mode = False
        forwards.append(u)
        prev, attr = u, "output"
    wf.forwards = forwards
    wf.loader = None
    wf.xla_step = None
    for u in forwards:
        u.initialize(device=None)
    for u in forwards:
        u.numpy_run()
    expected = numpy.array(forwards[-1].output.map_read().mem)

    from veles.export_inference import export_inference
    archive = os.path.join(tmp_path, "conv_archive")
    wf.name = "CxxConv"
    export_inference(wf, archive)
    got = _run_infer(engine, archive, x, str(tmp_path))
    assert got.shape == expected.shape
    numpy.testing.assert_allclose(got, expected, atol=1e-4)


def test_transformer_lm_matches_oracle(engine, tmp_path):
    """The whole LM stack (embedding + attention + layernorm + FFN +
    token_dense) runs forward in C++ and matches the numpy oracle."""
    prng.seed_all(66)
    from veles.znicz_tpu.models import transformer_lm
    saved = root.lm.loader.to_dict()
    saved_model = root.lm.model.to_dict()
    root.lm.loader.update({"minibatch_size": 16, "n_train": 64,
                           "n_valid": 32, "seq_len": 12})
    root.lm.model.update({"dim": 16, "heads": 4, "layers": 1,
                          "ffn_hidden": 32})
    root.lm.decision.max_epochs = 1
    try:
        wf = transformer_lm.create_workflow(name="CxxLM")
        wf.initialize(device="numpy")
        wf.run()
    finally:
        root.lm.loader.update(saved)
        root.lm.model.update(saved_model)
        root.lm.decision.max_epochs = 8
    archive = os.path.join(tmp_path, "lm_archive")
    wf.export_inference(archive)
    ids = numpy.array(wf.loader.minibatch_data.map_read().mem,
                      numpy.int32)
    wf.loader.minibatch_data.map_invalidate()
    wf.loader.minibatch_data.mem[...] = ids
    for f in wf.forwards:
        f.numpy_run()
    expected = numpy.array(wf.forwards[-1].output.map_read().mem)
    got = _run_infer(engine, archive, ids, str(tmp_path))
    assert got.shape == expected.shape
    numpy.testing.assert_allclose(got, expected, atol=1e-4)


def test_export_rejects_unsupported(tmp_path):
    """Units with no C++ counterpart must fail loudly, not silently
    skip (archive/runtime drift protection)."""
    wf = _train_mnist(tmp_path)
    from veles.znicz_tpu.ops.kohonen import KohonenForward
    wf.forwards.append(
        KohonenForward(wf, shape=(4, 4)))
    with pytest.raises(ValueError, match="no C\\+\\+ engine"):
        wf.export_inference(os.path.join(tmp_path, "bad"))


def _train_lm_variant(name, model_extra, seed):
    prng.seed_all(seed)
    from veles.znicz_tpu.models import transformer_lm
    saved = root.lm.loader.to_dict()
    saved_model = root.lm.model.to_dict()
    root.lm.loader.update({"minibatch_size": 16, "n_train": 64,
                           "n_valid": 32, "seq_len": 12})
    root.lm.model.update({"dim": 16, "heads": 4, "layers": 2,
                          "ffn_hidden": 32})
    root.lm.model.update(model_extra)
    root.lm.decision.max_epochs = 1
    try:
        wf = transformer_lm.create_workflow(name=name)
        wf.initialize(device="numpy")
        wf.run()
    finally:
        root.lm.loader.update(saved)
        root.lm.model.update(saved_model)
        root.lm.decision.max_epochs = 8
    return wf


def _lm_oracle_vs_engine(engine, tmp_path, wf, archive_name):
    archive = os.path.join(tmp_path, archive_name)
    wf.export_inference(archive)
    ids = numpy.array(wf.loader.minibatch_data.map_read().mem,
                      numpy.int32)
    wf.loader.minibatch_data.map_invalidate()
    wf.loader.minibatch_data.mem[...] = ids
    for f in wf.forwards:
        f.numpy_run()
    expected = numpy.array(wf.forwards[-1].output.map_read().mem)
    got = _run_infer(engine, archive, ids, str(tmp_path))
    assert got.shape == expected.shape
    numpy.testing.assert_allclose(got, expected, atol=1e-4)


def test_moe_lm_matches_oracle(engine, tmp_path):
    """The MoE LM (top-1 routing incl. the capacity-drop rule) runs
    forward in C++ and matches the numpy oracle exactly."""
    wf = _train_lm_variant(
        "CxxMoE", {"moe_experts": 4, "moe_capacity_factor": 1.0},
        seed=77)
    _lm_oracle_vs_engine(engine, tmp_path, wf, "moe_archive")


def test_stacked_lm_matches_oracle(engine, tmp_path):
    """The fused transformer_stack unit (stacked per-layer params)
    runs forward in C++ and matches the numpy oracle."""
    wf = _train_lm_variant("CxxStack", {"stacked": True}, seed=78)
    _lm_oracle_vs_engine(engine, tmp_path, wf, "stack_archive")


def test_cxx_generate_matches_python(engine, tmp_path):
    """veles_infer --generate: C++ greedy decode over the exported LM
    == the Python KV-cached greedy decode."""
    from veles.znicz_tpu.generate import generate
    wf = _train_lm_variant("CxxGen", {}, seed=81)
    archive = os.path.join(tmp_path, "gen_archive")
    wf.export_inference(archive)
    prompt = numpy.array([[1, 2, 3, 1, 2, 3, 1, 2],
                          [5, 6, 5, 6, 5, 6, 5, 6]], numpy.float32)
    inp = os.path.join(tmp_path, "prompt.npy")
    outp = os.path.join(tmp_path, "gen.npy")
    numpy.save(inp, prompt)
    subprocess.run(
        [os.path.join(engine, "veles_infer"), archive, inp, outp,
         "--generate", "6"],
        check=True, capture_output=True)
    got = numpy.load(outp).astype(numpy.int32)
    want = generate(wf, prompt.astype(numpy.int32), 6,
                    temperature=0.0)
    assert (got == want).all(), (got, want)


def test_autoencoder_matches_oracle(engine, tmp_path):
    """The MnistAE path (conv → pooling → depooling → deconv) exports
    and runs forward in C++, matching the numpy oracle."""
    prng.seed_all(91)
    from veles.znicz_tpu.models import mnist_ae
    saved = root.mnist_ae.loader.to_dict()
    saved_epochs = root.mnist_ae.decision.get("max_epochs")
    root.mnist_ae.loader.update({"minibatch_size": 25, "n_train": 100,
                                 "n_valid": 50})
    root.mnist_ae.decision.max_epochs = 1
    try:
        wf = mnist_ae.create_workflow(name="CxxAE")
        wf.initialize(device="numpy")
        wf.run()
    finally:
        root.mnist_ae.loader.update(saved)
        root.mnist_ae.decision.max_epochs = saved_epochs
    archive = os.path.join(tmp_path, "ae_archive")
    wf.export_inference(archive)
    x = numpy.array(wf.loader.minibatch_data.map_read().mem,
                    numpy.float32)
    expected = _forward_oracle(wf, x)
    got = _run_infer(engine, archive, x, str(tmp_path))
    assert got.shape == expected.shape
    numpy.testing.assert_allclose(got, expected, atol=1e-4)
