"""Per-tenant observability & QoS (ISSUE 18).

The acceptance path: a tenant table (``--tenants``) turns the serving
plane multi-tenant — bounded identity off the ``x-veles-tenant``
header, token-bucket quotas answering 429 + Retry-After, weighted-fair
scheduling in BOTH batchers so one tenant's burst cannot starve
another, tenant-labelled telemetry with per-tenant p99 SLOs, and the
``velescli loadgen`` open-loop harness proving capacity against a real
routed 2-replica fleet while an abusive tenant and a browned-out
replica (chaos) try to ruin the compliant tenant's day.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy
import pytest

from veles import fleet, health, reactor, telemetry
from veles.chaos import BrownoutProxy
from veles.router import EJECTED, FleetController, RouterFrontend
from veles.serving import tenants


def wait_until(fn, timeout=15.0, interval=0.01, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = fn()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError("timed out waiting for %s" % what)


def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.load(resp), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, json.load(exc), dict(exc.headers)


def _post(url, doc, headers=None, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers=dict({"Content-Type": "application/json"},
                     **(headers or {})))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.load(resp), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, json.load(exc), dict(exc.headers)


#: the table most tests install: one gold tenant, one metered silver
#: tenant, one best-effort batch class; anon stays unmetered
def _mk_table(**overrides):
    doc = {
        "default": "anon",
        "slo": {"p99_ms": 500.0},
        "tenants": {
            "acme": {"priority": "gold"},
            "hammer": {"rps": 5, "burst": 5, "priority": "silver"},
            "bulk": {"rps": 100, "priority": "batch"},
        },
    }
    doc.update(overrides)
    return tenants.TenantTable.from_dict(doc)


# -- shared tiny classifier artifact (hand-written, no training) -------


@pytest.fixture(scope="module")
def clf_archive(tmp_path_factory):
    """A 4->4 dense archive built by hand — instant to load, prices a
    real numpy forward through the full predict path."""
    base = tmp_path_factory.mktemp("tenants")
    numpy.save(base / "fc_weights.npy",
               numpy.eye(4, dtype=numpy.float32))
    (base / "contents.json").write_text(json.dumps({
        "format": 1, "workflow": "clf", "input_sample_shape": [4],
        "units": [{"type": "all2all", "name": "fc",
                   "config": {"neurons": 4,
                              "output_sample_shape": [4]},
                   "weights": "fc_weights.npy", "bias": None}]}))
    return str(base)


def _mk_frontend(clf_archive, **registry_kw):
    from veles.serving import ModelRegistry
    from veles.serving.frontend import ServingFrontend
    reg = ModelRegistry(backend="numpy", **registry_kw)
    reg.load("clf", clf_archive)
    front = ServingFrontend(reg, port=0)
    return reg, front, "http://127.0.0.1:%d" % front.port


# -- unit: table / quotas / resolver -----------------------------------


def test_token_bucket_quota_and_retry_after():
    q = tenants.TenantQuota("t", rps=10.0, burst=5.0)
    now = 100.0
    q._stamp = now                          # injectable test clock
    for _ in range(5):                      # burst drains
        ok, retry = q.admit(now)
        assert ok and retry == 0.0
    ok, retry = q.admit(now)
    assert not ok
    assert retry == pytest.approx(0.1)      # 1 token @ 10 rps
    ok, _ = q.admit(now + 0.11)             # refilled one token
    assert ok
    ok, _ = q.admit(now + 0.11)
    assert not ok
    # burst is the refill ceiling, however long the idle gap
    ok, retry = q.admit(now + 1000.0, cost=5.0)
    assert ok
    assert not q.admit(now + 1000.0)[0]
    # unmetered tenant never says no
    free = tenants.TenantQuota("free")
    assert free.admit(0.0, cost=1e9) == (True, 0.0)


def test_table_resolver_bounds_identity():
    table = _mk_table()
    assert table.resolve("acme") == "acme"
    assert table.resolve(None) == "anon"
    assert table.resolve("") == "anon"
    # unknown keys — the internet — fold into ONE bucket
    assert table.resolve("mallory'; drop table tenants;") == "other"
    assert table.resolve("x" * 4096) == "other"
    assert set(table.names()) == {"acme", "hammer", "bulk", "anon",
                                  "other"}
    # weights follow priority classes; best-effort = batch class only
    assert table.weight("acme") == 4.0
    assert table.weight("hammer") == 2.0
    assert table.weight("bulk") == table.weight("other") == 1.0
    assert table.best_effort("bulk")
    assert not table.best_effort("acme")
    doc = table.describe()
    assert doc["default"] == "anon"
    assert doc["tenants"]["anon"]["default"] is True
    assert doc["tenants"]["hammer"]["rps"] == 5.0
    assert doc["tenants"]["acme"]["rps"] is None    # unmetered
    assert doc["tenants"]["acme"]["weight"] == 4.0


def test_table_config_validation():
    with pytest.raises(ValueError, match="unknown key"):
        tenants.TenantTable.from_dict({"tenant": {}})   # typo'd key
    with pytest.raises(ValueError, match="unknown priority"):
        tenants.TenantTable.from_dict(
            {"tenants": {"a": {"priority": "platinum"}}})
    with pytest.raises(ValueError, match="rps must be"):
        tenants.TenantTable.from_dict({"tenants": {"a": {"rps": 0}}})
    with pytest.raises(ValueError, match="unknown key"):
        tenants.TenantTable.from_dict(
            {"tenants": {"a": {"qps": 5}}})
    with pytest.raises(ValueError, match="JSON object"):
        tenants.TenantTable.from_dict([])
    # no table installed -> every tenant weighs 1 (FIFO-equivalent)
    assert tenants.get_table() is None
    assert tenants.weight("whoever") == 1.0


# -- unit: weighted-fair micro-batcher ---------------------------------


def test_micro_batcher_weighted_fair_order():
    """With the dispatch loop held open, a gold tenant submitted LAST
    is served before the bronze backlog (virtual finish times), and
    each tenant's own requests keep FIFO order."""
    from veles.serving import MicroBatcher
    tenants.set_table(tenants.TenantTable.from_dict({"tenants": {
        "gold": {"priority": "gold"},
        "plain": {"priority": "bronze"}}}))
    order = []
    started = threading.Event()
    release = threading.Event()
    first = {"seen": False}

    def run_batch(rows):
        if not first["seen"]:
            first["seen"] = True
            started.set()
            release.wait(30)
        else:
            order.append(int(rows[0, 0]))
        return rows, rows.shape[0]

    b = MicroBatcher(run_batch, max_batch=1, max_wait_ms=1.0)
    results = []

    def client(i, tenant):
        results.append(b.predict(
            numpy.full((1, 4), float(i), numpy.float32),
            tenant=tenant))

    try:
        blocker = threading.Thread(target=client, args=(0, None))
        blocker.start()
        started.wait(30)
        threads = []
        for i, tenant in ((1, "plain"), (2, "plain"), (3, "plain"),
                          (4, "gold")):
            t = threading.Thread(target=client, args=(i, tenant))
            t.start()
            threads.append(t)
            wait_until(lambda n=i: b._queued_rows >= n,
                       what="request %d queued" % i)
        release.set()
        blocker.join(30)
        for t in threads:
            t.join(30)
        # gold's vft = 1/4 jumps the bronze backlog (1, 2, 3) even
        # though it arrived last; bronze stays FIFO among itself
        assert order == [4, 1, 2, 3]
        assert len(results) == 5
    finally:
        b.close()


# -- unit: weighted-fair continuous batcher (real decode plane) --------


def test_continuous_batcher_fair_grant_no_starvation(tmp_path):
    """One KV slot, a queued bronze backlog, a gold request arriving
    last: the freed slot goes to gold first, and EVERY queued request
    still completes (zero cross-tenant starvation)."""
    from test_decode import _export_lm
    from veles.serving import (ArchiveModel, ContinuousBatcher,
                               GenerativeEngine)
    tenants.set_table(tenants.TenantTable.from_dict({"tenants": {
        "gold": {"priority": "gold"},
        "plain": {"priority": "bronze"}}}))
    _, archive = _export_lm(tmp_path, "TenantLM")
    engine = GenerativeEngine(ArchiveModel.from_dir(archive),
                              n_slots=1, max_len=64)
    batcher = ContinuousBatcher(engine, max_queue=8, model="lm")
    done_order = []
    lock = threading.Lock()
    try:
        blocker = batcher.submit([1, 2, 3], max_tokens=24,
                                 tenant="plain")
        wait_until(lambda: len(blocker.tokens) >= 2,
                   what="blocker decoding")
        handles = [
            ("plain-1", batcher.submit([1, 2], max_tokens=4,
                                       tenant="plain")),
            ("plain-2", batcher.submit([2, 3], max_tokens=4,
                                       tenant="plain")),
            ("gold", batcher.submit([3, 4], max_tokens=4,
                                    tenant="gold")),
        ]

        def waiter(name, handle):
            handle.wait(120)
            with lock:
                done_order.append(name)

        threads = [threading.Thread(target=waiter, args=(n, h))
                   for n, h in handles]
        for t in threads:
            t.start()
        assert blocker.wait(120)
        for t in threads:
            t.join(120)
        # all three completed (no starvation), gold first: its
        # virtual finish time (cost/4) undercuts the bronze backlog
        assert sorted(done_order) == ["gold", "plain-1", "plain-2"]
        assert done_order[0] == "gold"
        assert engine.pool.in_use == 0
        # token attribution rode along
        reg = telemetry.get_registry()
        assert reg.counter_total("veles_serving_tenant_tokens_total",
                                 tenant="gold") >= 4
        assert reg.counter_total("veles_serving_tenant_tokens_total",
                                 tenant="plain") >= 24
    finally:
        batcher.close()


# -- HTTP: quotas, /debug/tenants, bounded tenant series ---------------


def test_http_quota_429_debug_doc_and_bounded_series(clf_archive):
    reg = front = None
    try:
        reg, front, base = _mk_frontend(clf_archive)
        # no table installed: /debug/tenants says so, traffic flows
        code, doc, _ = _get(base + "/debug/tenants")
        assert code == 404 and "tenants" in doc["error"]
        tenants.set_table(_mk_table())
        body = {"model": "clf", "inputs": [[1.0, 2.0, 3.0, 4.0]]}

        # gold tenant: unmetered, all 200
        for _ in range(8):
            code, doc, _ = _post(base + "/v1/predict", body,
                                 headers={"x-veles-tenant": "acme"})
            assert code == 200
        # metered tenant: burst of 5, then 429 + honest Retry-After
        # (the loop may straddle a refill instant, so allow 5-6 hits)
        answers = [_post(base + "/v1/predict", body,
                         headers={"x-veles-tenant": "hammer"})
                   for _ in range(8)]
        codes = [c for c, _, _ in answers]
        n_429 = codes.count(429)
        assert codes.count(200) in (5, 6)
        assert n_429 >= 2 and codes.count(200) + n_429 == 8
        rejected = next(a for a in answers if a[0] == 429)
        assert "quota" in rejected[1]["error"]
        assert rejected[1]["retry_after_s"] > 0
        assert float(rejected[2]["Retry-After"]) > 0
        # unknown keys fold into ONE bucket — the internet cannot
        # mint series
        for key in ("mallory-1", "mallory-2", "mallory-3"):
            code, _, _ = _post(base + "/v1/predict", body,
                               headers={"x-veles-tenant": key})
            assert code == 200

        # /debug/tenants: live bucket levels, cached-doc cheap
        code, doc, _ = _get(base + "/debug/tenants")
        assert code == 200
        assert doc["tenants"]["hammer"]["tokens"] < 5
        assert doc["tenants"]["acme"]["priority"] == "gold"

        # the scrape surface: tenant-labelled series with BOUNDED
        # cardinality (configured names + anon + other, nothing else)
        metrics = fleet.parse_prometheus(
            telemetry.get_registry().render_prometheus())
        table = tenants.get_table()
        for name in ("veles_serving_tenant_requests_total",
                     "veles_serving_rejected_total",
                     "veles_serving_tenant_latency_seconds_count"):
            seen = {dict(items)["tenant"]
                    for (n, items) in metrics
                    if n == name and "tenant" in dict(items)}
            assert seen, name
            assert seen <= set(table.names()), name
        reg_t = telemetry.get_registry()
        assert reg_t.counter_total(
            "veles_serving_tenant_requests_total",
            tenant="other") == 3
        assert reg_t.counter_total(
            "veles_serving_rejected_total",
            reason="quota", tenant="hammer") == n_429

        # scrape_target folds the tenant families into the top row...
        row = fleet.scrape_target(base, timeout=5.0)
        by_tenant = row["metrics"]["tenants"]
        assert by_tenant["hammer"]["requests"] == 8
        assert by_tenant["hammer"]["rejected"] == n_429
        assert by_tenant["other"]["requests"] == 3
        # ... and velescli top renders the per-tenant line
        rendered = fleet.render_snapshot(fleet.fleet_snapshot([base]))
        assert "tenants " in rendered
        assert "hammer: req 8" in rendered
        assert "shed 3" in rendered
    finally:
        if front is not None:
            front.close()
        if reg is not None:
            reg.close()


def test_top_degrades_silently_on_pre_tenant_target():
    """A probe-only (pre-PR-18) target exports no tenant families:
    the scrape row must carry no 'tenants' key and the rendered top
    view no tenants line — not an error row."""
    def route(request):
        if request.path.startswith("/healthz"):
            request.reply_json(200, {"status": "ok"})
        elif request.path.startswith("/readyz"):
            request.reply_json(200, {"ready": True, "reasons": [],
                                     "checks": {}, "slos": {}})
        elif request.path.startswith("/metrics"):
            request.reply(200, b'veles_serving_queue_rows{model="m"}'
                          b' 0\n', "text/plain")
        else:
            request.reply_json(404, {"error": "nope"})

    server = reactor.HttpServer("127.0.0.1", 0, route, name="pre18")
    url = "http://127.0.0.1:%d" % server.port
    try:
        row = fleet.scrape_target(url, timeout=5.0)
        assert row["ready"] is True
        assert "tenants" not in row["metrics"]
        rendered = fleet.render_snapshot(fleet.fleet_snapshot([url]))
        assert "tenants " not in rendered
        assert "error" not in rendered.lower()
    finally:
        server.close()


def test_best_effort_tenant_sheds_first_under_pressure(clf_archive):
    """While the shedding check fires (excluded for everyone else),
    batch-class traffic is refused 503 BEFORE any compute."""
    reg = front = None
    try:
        reg, front, base = _mk_frontend(clf_archive)
        tenants.set_table(_mk_table())
        monitor = health.get_monitor()
        monitor.add_check("serving:99:shedding",
                          lambda: (False, "shed ratio 0.9"))
        monitor.tick()
        body = {"model": "clf", "inputs": [[0.0, 0.0, 0.0, 0.0]]}
        code, doc, _ = _post(base + "/v1/predict", body,
                             headers={"x-veles-tenant": "bulk"})
        assert code == 503 and "best-effort" in doc["error"]
        # a paying tenant still rides through the excluded check
        code, _, _ = _post(base + "/v1/predict", body,
                           headers={"x-veles-tenant": "acme"})
        assert code == 200
        assert telemetry.get_registry().counter_total(
            "veles_serving_rejected_total",
            reason="priority", tenant="bulk") == 1
    finally:
        if front is not None:
            front.close()
        if reg is not None:
            reg.close()


# -- per-tenant SLOs ----------------------------------------------------


def test_tenant_p99_slo_template_fires_on_breach():
    table = _mk_table()
    monitor = health.get_monitor()
    names = table.install_slos(monitor)
    assert "tenant_p99:acme" in names
    assert len(names) == len(table.names())
    hist = telemetry.histogram(
        "veles_serving_tenant_latency_seconds",
        "per-tenant serving latency", labels=("tenant",))
    # acme breaches its 500ms objective on every sample; hammer stays
    # comfortably inside it
    for _ in range(20):
        hist.labels("acme").observe(2.0)
        hist.labels("hammer").observe(0.005)
    now = time.time()
    monitor.tick(now=now)
    monitor.tick(now=now + 1.0)
    by_name = {slo.name: slo for slo in monitor.slos()}
    assert by_name["tenant_p99:acme"].firing
    assert not by_name["tenant_p99:hammer"].firing
    ready, reasons = monitor.ready_state()
    assert not ready
    assert any("tenant_p99:acme" in r for r in reasons)


# -- router: latency-aware policy, tenant attribution ------------------


def _row(url, p99=None, queue=0.0):
    metrics = {"serving_queue_rows": queue}
    if p99 is not None:
        metrics["serving_p99_s"] = p99
    return {"url": url, "reachable": True, "ready": True,
            "firing": [], "reasons": [], "metrics": metrics}


def test_router_latency_policy_selection():
    a, b = "http://a:1", "http://b:1"
    with pytest.raises(ValueError, match="routing"):
        FleetController([a], routing_policy="fastest")
    c = FleetController([a, b], interval=999.0,
                        routing_policy="latency")
    try:
        # scrape plumbing: p99 rides the row into the replica state
        c.tick(rows=[_row(a, p99=0.5), _row(b, p99=0.01)])
        assert c._replicas[a].p99_s == 0.5
        assert c._replicas[a].describe()["p99_s"] == 0.5
        assert c.select().url == b          # faster replica wins
        # queue pressure prices in: fast-but-deep loses to
        # slower-but-idle
        c.tick(rows=[_row(a, p99=0.05), _row(b, p99=0.01, queue=10)])
        assert c.select().url == a
        # a replica with UNKNOWN p99 (pre-18, or no traffic yet)
        # prices at the fleet median — neither magnet nor pariah
        c.tick(rows=[_row(a), _row(b, p99=0.02)])
        assert c._replicas[a].p99_s is None
        assert c.select().url == a          # tie -> url order
        # nobody scraped a p99 yet -> least-queue fallback
        c.tick(rows=[_row(a, queue=3.0), _row(b, queue=0.0)])
        assert c.select().url == b
    finally:
        c.close()


def test_fleet_histogram_quantile():
    text = "\n".join([
        'veles_x_seconds_bucket{le="0.1"} 50',
        'veles_x_seconds_bucket{le="0.5"} 90',
        'veles_x_seconds_bucket{le="+Inf"} 100',
        'veles_x_seconds_count 100',
    ]) + "\n"
    metrics = fleet.parse_prometheus(text)
    # p50 interpolates inside the first bucket; p99 lands in +Inf ->
    # clamped to the last finite bound
    assert fleet.histogram_quantile(metrics, "veles_x_seconds", 0.5) \
        == pytest.approx(0.1)
    assert fleet.histogram_quantile(metrics, "veles_x_seconds", 0.95) \
        == pytest.approx(0.5)
    assert fleet.histogram_quantile(metrics, "veles_x_seconds", 0.99) \
        == pytest.approx(0.5)
    assert fleet.histogram_quantile(metrics, "veles_nope", 0.5) is None


# -- chaos: abusive tenant + browned-out replica -----------------------


def test_chaos_abusive_tenant_and_brownout(clf_archive):
    """The ISSUE 18 chaos scenario: one tenant floods at 10x its
    quota while one of two replicas browns out. The compliant
    tenant's requests all answer 200 through the healthy replica, its
    per-tenant p99 SLO stays quiet, and the abusive tenant's quota
    shed counter climbs."""
    table = tenants.set_table(_mk_table())
    monitor = health.get_monitor()
    table.install_slos(monitor)
    reg_a = front_a = reg_b = front_b = None
    proxy = controller = router = None
    try:
        reg_a, front_a, base_a = _mk_frontend(clf_archive)
        reg_b, front_b, base_b = _mk_frontend(clf_archive)
        proxy = BrownoutProxy(("127.0.0.1", front_a.port))
        controller = FleetController([proxy.url, base_b],
                                     interval=0.3, scrape_timeout=0.5)
        router = RouterFrontend(controller, port=0)
        rbase = router.url
        wait_until(lambda: _get(rbase + "/router/status")[1][
            "admitted"] == 2, what="both replicas admitted")
        # chaos on: replica A's pipe crawls; the control loop ejects
        # it on scrape timeout, traffic drains to B
        proxy.brownout(2.0)
        wait_until(lambda: any(
            bk["state"] == EJECTED and bk["url"] == proxy.url
            for bk in _get(rbase + "/router/status")[1]["backends"]),
            what="brownout ejection")

        body = {"model": "clf", "inputs": [[1.0, 0.0, 0.0, 0.0]]}
        abusive_codes = []

        abusive_retry_after = []

        def abuse():
            # 10x the 5 rps quota, no pacing: the bucket must dry up
            for _ in range(50):
                code, _, hdrs = _post(rbase + "/v1/predict", body,
                                      headers={"x-veles-tenant":
                                               "hammer"})
                abusive_codes.append(code)
                if code == 429:
                    abusive_retry_after.append(
                        hdrs.get("Retry-After"))

        abuser = threading.Thread(target=abuse)
        abuser.start()
        compliant_codes = []
        for _ in range(30):
            code, _, _ = _post(rbase + "/v1/predict", body,
                               headers={"x-veles-tenant": "acme"})
            compliant_codes.append(code)
            time.sleep(0.005)
        abuser.join(60)

        # zero starvation: every compliant request answered 200
        assert compliant_codes == [200] * 30
        # the abusive tenant hit the wall: 429s, counted per-tenant,
        # each carrying the replica bucket's Retry-After THROUGH the
        # router hop (the generic forward path must not drop it)
        assert abusive_codes.count(429) >= 20
        assert abusive_retry_after and all(
            ra is not None and float(ra) > 0
            for ra in abusive_retry_after)
        reg = telemetry.get_registry()
        shed = reg.counter_total("veles_serving_rejected_total",
                                 reason="quota", tenant="hammer")
        assert shed == abusive_codes.count(429)
        assert reg.counter_total("veles_serving_rejected_total",
                                 tenant="acme") == 0
        # router attribution saw both tenants
        assert reg.counter_total("veles_router_requests_total",
                                 tenant="acme") == 30
        # the compliant tenant's p99 SLO exists AND is not firing
        monitor.tick()
        by_name = {slo.name: slo for slo in monitor.slos()}
        assert "tenant_p99:acme" in by_name
        assert not by_name["tenant_p99:acme"].firing
    finally:
        for closable in (router, controller, proxy, front_a, front_b,
                         reg_a, reg_b):
            if closable is not None:
                closable.close()


# -- loadgen: the open-loop proof harness ------------------------------


def test_loadgen_parse_and_mix():
    from veles import loadgen
    with pytest.raises(SystemExit):
        loadgen._parse_tenants([":0.5"])
    with pytest.raises(SystemExit):
        loadgen._parse_tenants(["a:lots"])
    mix = loadgen._TenantMix(loadgen._parse_tenants(
        ["acme:3", "free"]))
    assert mix.names == ["acme", "free"]
    import random
    rng = random.Random(7)
    picks = [mix.pick(rng) for _ in range(2000)]
    share = picks.count("acme") / len(picks)
    assert 0.70 <= share <= 0.80            # 3:1 mix, seeded draw
    # open-loop percentile helper
    assert loadgen._percentile([], 0.99) is None
    assert loadgen._percentile([1.0, 2.0, 3.0, 4.0], 0.5) == 2.0


def test_loadgen_refuses_hostile_geometry():
    """Admission hardening (zlint untrusted-geometry): the
    /v1/models listing is the TARGET's data — a malicious or buggy
    target advertising a huge input_sample_shape must not make the
    load generator allocate it."""
    from veles import loadgen
    assert loadgen._validated_shape([4, 4]) == [4, 4]
    assert loadgen._validated_shape([]) == [1]
    assert loadgen._validated_shape([0, 3]) == [1, 3]
    with pytest.raises(SystemExit, match="refusing"):
        loadgen._validated_shape([1 << 30])
    with pytest.raises(SystemExit, match="refusing"):
        loadgen._validated_shape([2] * 9)          # rank cap
    with pytest.raises(SystemExit, match="non-numeric"):
        loadgen._validated_shape(["lots"])


def test_loadgen_e2e_routed_fleet(clf_archive, capsys):
    """The acceptance run: loadgen drives a tenant mix at a REAL
    routed 2-replica fleet and reports per-tenant curves plus the
    routed_capacity_rps_at_p99_slo row."""
    from veles.loadgen import loadgen_main
    tenants.set_table(_mk_table())
    reg_a = front_a = reg_b = front_b = None
    controller = router = None
    try:
        reg_a, front_a, base_a = _mk_frontend(clf_archive)
        reg_b, front_b, base_b = _mk_frontend(clf_archive)
        controller = FleetController([base_a, base_b], interval=0.3,
                                     scrape_timeout=1.0,
                                     routing_policy="latency")
        router = RouterFrontend(controller, port=0)
        wait_until(lambda: _get(router.url + "/router/status")[1][
            "admitted"] == 2, what="both replicas admitted")
        rc = loadgen_main([
            router.url, "--tenant", "acme:3", "--tenant", "free",
            "--rps", "10", "--rps", "25", "--duration", "1.2",
            "--p99-slo-ms", "2000", "--seed", "99", "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["metric"] == "routed_capacity_rps_at_p99_slo"
        # the tiny fleet holds both offered stages inside a 2s p99
        assert report["value"] == 25.0
        assert report["extra"]["compliant_tenant"] == "acme"
        stages = report["extra"]["stages"]
        assert [s["offered_rps"] for s in stages] == [10.0, 25.0]
        for stage in stages:
            for name in ("acme", "free"):
                t = stage["tenants"][name]
                assert t["offered"] > 0
                assert t["ok"] + t["shed"] + t["errors"] \
                    == t["offered"]
                assert t["errors"] == 0
                assert t["p99_ms"] is not None
        # open-loop accounting: offered tracks rate x duration, and
        # the tenant mix roughly honored its 3:1 shares
        s = stages[1]["tenants"]
        total = s["acme"]["offered"] + s["free"]["offered"]
        assert total >= 15                  # 25 rps x 1.2 s, jittered
        assert s["acme"]["offered"] > s["free"]["offered"]
        # both replicas actually served routed traffic
        reg = telemetry.get_registry()
        for url in (base_a, base_b):
            assert reg.counter_total("veles_router_requests_total",
                                     replica=url, outcome="ok") > 0
    finally:
        for closable in (router, controller, front_a, front_b,
                         reg_a, reg_b):
            if closable is not None:
                closable.close()


def test_loadgen_cli_parsers():
    from veles.loadgen import build_loadgen_argparser
    args = build_loadgen_argparser().parse_args(
        ["http://x:1", "--tenant", "a:2", "--rps", "5", "--json"])
    assert args.target == "http://x:1"
    assert args.tenant == ["a:2"] and args.rps == [5.0]
    # the serve/route CLIs grew their QoS knobs
    from veles.router import build_route_argparser
    ra = build_route_argparser().parse_args(
        ["http://a:1", "--routing-policy", "latency",
         "--tenants", "/tmp/t.json"])
    assert ra.routing_policy == "latency"
    assert ra.tenants == "/tmp/t.json"
    from veles.serving.frontend import build_serve_argparser
    sa = build_serve_argparser().parse_args(
        ["--model", "m=/x", "--tenants", "/tmp/t.json"])
    assert sa.tenants == "/tmp/t.json"
