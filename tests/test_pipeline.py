"""TransformerBlockStack + GPipe pipeline parallelism: numpy↔scan
parity, jax.grad oracle on the stacked backward, pipeline == scan
equivalence on the virtual mesh (PP and DP×PP), and the stacked LM
sample training through the pipe from config alone."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root
from veles.znicz_tpu.ops.transformer_stack import TransformerBlockStack
from veles.znicz_tpu.parallel import pipeline as PL

from tests.test_conv_stack import (
    build, xla_forward, xla_backward, grad_oracle)


STACK_CASES = [
    (TransformerBlockStack, dict(layers=2, heads=2, hidden=16)),
    (TransformerBlockStack, dict(layers=3, heads=4, hidden=8,
                                 causal=False)),
]


@pytest.mark.parametrize("cls,kwargs", STACK_CASES,
                         ids=lambda v: str(v)[:40])
def test_stack_forward_parity(cls, kwargs):
    wf, feed, fwd, gd, x, err, comp = build(
        cls, input_shape=(2, 6, 8), gd_kwargs={}, **kwargs)
    golden = numpy.array(fwd.output.mem)
    y = xla_forward(comp, feed, fwd, comp.gather_params(), x)
    assert numpy.allclose(numpy.asarray(y), golden, atol=3e-5), \
        numpy.abs(numpy.asarray(y) - golden).max()


@pytest.mark.parametrize("cls,kwargs", STACK_CASES,
                         ids=lambda v: str(v)[:40])
def test_stack_backward_vs_jax_grad(cls, kwargs):
    wf, feed, fwd, gd, x, err, comp = build(
        cls, input_shape=(2, 6, 8), gd_kwargs={}, **kwargs)
    params0 = comp.gather_params()
    state0 = comp.gather_state()
    gd.numpy_run()
    ei_np = numpy.array(gd.err_input.mem)
    ei_x, params1 = xla_backward(comp, feed, fwd, gd, params0, state0,
                                 x, err)
    gp, gx = grad_oracle(comp, feed, fwd, params0, x, err)
    assert numpy.allclose(ei_np, numpy.asarray(gx), atol=3e-4), \
        numpy.abs(ei_np - numpy.asarray(gx)).max()
    assert numpy.allclose(ei_np, numpy.asarray(ei_x), atol=3e-4)
    for pname, grad_tree in gp.get(fwd.name, {}).items():
        w0 = numpy.array(params0[fwd.name][pname])
        w1_np = getattr(fwd, pname).map_read().mem
        w1_x = numpy.asarray(params1[fwd.name][pname])
        oracle = numpy.asarray(grad_tree)
        assert numpy.allclose(w0 - w1_np, oracle, atol=5e-4), pname
        assert numpy.allclose(w0 - w1_x, oracle, atol=5e-4), pname


def test_stack_remat_matches_plain():
    """The remat path (stash layer inputs, recompute caches in the
    backward — VERDICT r4 #3) is numerically identical to the full-
    stash scan: same y, dx, every grad leaf; and its stash really is
    just the (L, B, S, D) inputs, not the O(L·B·H·S²) cache tree."""
    import jax
    import jax.numpy as jnp

    prng.seed_all(91)
    gen = prng.get("remat")
    L, B, S, D, H, heads = 3, 2, 8, 16, 32, 4
    x = gen.normal(0, 1.0, (B, S, D)).astype(numpy.float32)
    err = gen.normal(0, 1.0, (B, S, D)).astype(numpy.float32)
    params = {}
    shapes = {"weights": (L, D, 3 * D), "bias": (L, 3 * D),
              "weights_out": (L, D, D), "bias_out": (L, D),
              "ln1_g": (L, D), "ln1_b": (L, D),
              "ffn_w1": (L, D, H), "ffn_b1": (L, H),
              "ffn_w2": (L, H, D), "ffn_b2": (L, D),
              "ln2_g": (L, D), "ln2_b": (L, D)}
    for k, shp in shapes.items():
        if k.endswith("_g"):
            params[k] = numpy.ones(shp, numpy.float32)
        elif "bias" in k or k.endswith("_b"):
            params[k] = numpy.zeros(shp, numpy.float32)
        else:
            params[k] = gen.normal(0, 0.3, shp).astype(numpy.float32)
    y0, caches = jax.jit(lambda p, xx: PL.stack_fwd(
        p, xx, heads, True, 1e-5))(params, x)
    dx0, g0 = jax.jit(lambda p, c, e: PL.stack_bwd(
        p, c, e, heads, 1e-5))(params, caches, err)
    y1, xs = jax.jit(lambda p, xx: PL.stack_fwd_remat(
        p, xx, heads, True, 1e-5))(params, x)
    dx1, g1 = jax.jit(lambda p, c, e: PL.stack_bwd_remat(
        p, c, e, heads, True, 1e-5))(params, xs, err)
    assert xs.shape == (L, B, S, D)       # inputs only, no cache tree
    assert numpy.allclose(numpy.asarray(y0), numpy.asarray(y1),
                          atol=1e-6)
    assert numpy.allclose(numpy.asarray(dx0), numpy.asarray(dx1),
                          atol=1e-5)
    for k in g0:
        assert numpy.allclose(numpy.asarray(g0[k]),
                              numpy.asarray(g1[k]), atol=1e-5), k


def test_stacked_lm_remat_trains_identically():
    """root.lm.model.remat through the workflow: identical training
    history to the full-stash run (remat is a memory knob, not a math
    change)."""
    h0 = [e["validation"]["metric"] for e in
          _run_stacked_lm("xla", epochs=3).decision.history]
    root.lm.model.remat = True
    try:
        wf = _run_stacked_lm("xla", epochs=3)
    finally:
        root.lm.model.remat = False
    stack = next(f for f in wf.forwards
                 if isinstance(f, TransformerBlockStack))
    assert stack.remat
    h1 = [e["validation"]["metric"] for e in wf.decision.history]
    assert numpy.allclose(h0, h1, atol=1e-4), (h0, h1)


def _mesh(axes):
    import jax
    from veles.znicz_tpu import parallel
    return parallel.make_mesh(axes, jax.devices("cpu"))


@pytest.mark.parametrize("axes,batch_axis,n_micro", [
    ({"pipe": 4}, None, 4),
    ({"data": 2, "pipe": 4}, "data", 2),
], ids=["pp4", "dp2xpp4"])
def test_pipeline_matches_scan(axes, batch_axis, n_micro):
    """The GPipe schedule is a pure re-layout: forward outputs and
    backward (dx, grads) must equal the single-program scan path."""
    import jax
    import jax.numpy as jnp

    prng.seed_all(77)
    gen = prng.get("pp")
    L, B, S, D, H, heads = 4, 8, 6, 8, 16, 2
    mesh = _mesh(axes)
    x = gen.normal(0, 1.0, (B, S, D)).astype(numpy.float32)
    err = gen.normal(0, 1.0, (B, S, D)).astype(numpy.float32)
    params = {}
    shapes = {"weights": (L, D, 3 * D), "bias": (L, 3 * D),
              "weights_out": (L, D, D), "bias_out": (L, D),
              "ln1_g": (L, D), "ln1_b": (L, D),
              "ffn_w1": (L, D, H), "ffn_b1": (L, H),
              "ffn_w2": (L, H, D), "ffn_b2": (L, D),
              "ln2_g": (L, D), "ln2_b": (L, D)}
    for k, shp in shapes.items():
        if k.endswith("_g"):
            params[k] = numpy.ones(shp, numpy.float32)
        elif "bias" in k or k.endswith("_b"):
            params[k] = numpy.zeros(shp, numpy.float32)
        else:
            params[k] = gen.normal(0, 0.3, shp).astype(numpy.float32)

    y_ref, caches_ref = jax.jit(
        lambda p, xx: PL.stack_fwd(p, xx, heads, True, 1e-5))(params, x)
    dx_ref, g_ref = jax.jit(
        lambda p, c, e: PL.stack_bwd(p, c, e, heads, 1e-5))(
        params, caches_ref, err)

    y_pp, caches_pp = PL.pipeline_fwd(
        params, x, mesh, batch_axis=batch_axis, n_micro=n_micro,
        heads=heads, causal=True)
    assert numpy.allclose(numpy.asarray(y_pp), numpy.asarray(y_ref),
                          atol=2e-5), \
        numpy.abs(numpy.asarray(y_pp) - numpy.asarray(y_ref)).max()

    # DP adds a cross-shard gradient all-reduce whose accumulation
    # order depends on XLA's CPU thread partitioning — run-to-run
    # float noise on top of the re-layout, NOT seedable from here
    # (the known tier-1 flake; same inputs, different reduction
    # trees). 1e-3 absorbs that noise while staying falsifiable: a
    # real schedule/layout bug (wrong microbatch stitched, stale
    # stash) shows up as O(1e-1)+ disagreement.
    bwd_atol = 1e-3 if batch_axis else 2e-4
    dx_pp, g_pp = PL.pipeline_bwd(
        params, caches_pp, err, mesh, batch_axis=batch_axis,
        n_micro=n_micro, heads=heads)
    assert numpy.allclose(numpy.asarray(dx_pp),
                          numpy.asarray(dx_ref), atol=bwd_atol)
    for k in g_ref:
        assert numpy.allclose(numpy.asarray(g_pp[k]),
                              numpy.asarray(g_ref[k]),
                              atol=bwd_atol), k
    # the stash really is pipe/data-sharded, params-style
    leaf = caches_pp["x"]
    assert leaf.shape[1] == L


def _run_stacked_lm(backend, parallel_spec=None, seed=606,
                    epochs=6, loader_overrides=None):
    prng.seed_all(seed)
    from veles.znicz_tpu.models import transformer_lm
    root.lm.loader.update({"minibatch_size": 32, "n_train": 512,
                           "n_valid": 128, "seq_len": 16, "vocab": 8,
                           "max_period": 4})
    if loader_overrides:
        root.lm.loader.update(loader_overrides)
    root.lm.model.update({"dim": 32, "heads": 2, "layers": 4,
                          "ffn_hidden": 64, "moe_experts": 0,
                          "attn_block": None, "stacked": True})
    root.lm.decision.max_epochs = epochs
    root.lm.parallel.update({"seq": 1, "model": 1, "data": 1,
                             "expert": 1, "pipe": 1,
                             "microbatches": 4, "schedule": "gpipe"})
    if parallel_spec:
        root.lm.parallel.update(parallel_spec)
    wf = transformer_lm.create_workflow(
        name="StackLM_%s_%s" % (backend, parallel_spec))
    wf.initialize(device=backend)
    wf.run()
    # don't leak stacked/PP config into other test modules
    root.lm.model.stacked = False
    root.lm.parallel.update({"pipe": 1, "data": 1,
                             "schedule": "gpipe"})
    return wf


def test_stacked_lm_trains_and_pp_matches_single_device():
    """The stacked LM must train, and running the same model through
    the DP×PP pipeline must reproduce the single-device history.

    4 epochs, not 6 (the tier-1 de-flake, ISSUE 11 satellite): DP's
    gradient all-reduce accumulation order varies with XLA CPU
    thread partitioning run to run — unseedable ~1e-7/step noise
    that SGD amplifies CHAOTICALLY with horizon (measured: 6.5e-5
    history gap at epoch 4, 1.3e-2 at epoch 5, 3.0e-2 at epoch 6).
    The short horizon keeps atol=1e-2 both flake-proof (>100x the
    observed epoch-4 noise) and falsifiable (a dropped microbatch or
    wrong shard diverges by O(1) from step one); the STRICT DP×PP
    equivalence check is test_pipeline_matches_scan[dp2xpp4] above —
    same trick the 1f1b history test documents below."""
    wf1 = _run_stacked_lm("xla", epochs=4)
    h1 = [e["validation"]["metric"] for e in wf1.decision.history]
    assert h1[-1] < h1[0], h1
    wf8 = _run_stacked_lm("xla", {"pipe": 4, "data": 2,
                                  "microbatches": 4}, epochs=4)
    h8 = [e["validation"]["metric"] for e in wf8.decision.history]
    # 2e-2, not 1e-2 (ISSUE 15 satellite, PR-11 convention): on a
    # LOADED 2-CPU container the XLA thread-partitioning noise the
    # DP all-reduce amplifies lands above the idle-box 6.5e-5
    # epoch-4 measurement often enough to flake at 1e-2 (observed
    # ~1.2e-2 worst case under a full tier-1 run). Still falsifiable:
    # a dropped microbatch or wrong shard diverges by O(1e-1)+ from
    # step one, and the strict DP×PP equivalence check is
    # test_pipeline_matches_scan[dp2xpp4].
    assert numpy.allclose(h1, h8, atol=2e-2), (h1, h8)
    step = wf8.xla_step
    stacks = [f for f in wf8.forwards
              if type(f).__name__ == "TransformerBlockStack"]
    assert stacks and stacks[0].pipe_mesh is not None
    leaf = step.params[stacks[0].name]["weights"]
    assert len(leaf.sharding.device_set) == 8
    assert leaf.sharding.spec[0] == "pipe", leaf.sharding.spec
    # stage hops must survive into the partitioned HLO as
    # collective-permute; gradient sync over data as all-reduce
    from veles.znicz_tpu import parallel
    parallel.assert_collectives(
        step, ["collective-permute", "all-reduce"])


def test_stacked_lm_1f1b_leaf_for_leaf_vs_gpipe():
    """1F1B through the WORKFLOW (root.lm.parallel.schedule="1f1b"),
    leaf-for-leaf: after exactly ONE optimizer update (one train
    minibatch per epoch, one epoch) every stacked parameter must
    match the GPipe schedule's to float tolerance — the interleaved
    schedule plus forward recompute is a pure re-ordering of the same
    math."""
    tiny = {"n_train": 32, "n_valid": 32}
    wf_g = _run_stacked_lm("xla", {"pipe": 4, "microbatches": 4},
                           epochs=1, loader_overrides=tiny)
    wf_f = _run_stacked_lm("xla", {"pipe": 4, "microbatches": 4,
                                   "schedule": "1f1b"},
                           epochs=1, loader_overrides=tiny)
    stacks_g = [f for f in wf_g.forwards
                if type(f).__name__ == "TransformerBlockStack"]
    stacks_f = [f for f in wf_f.forwards
                if type(f).__name__ == "TransformerBlockStack"]
    assert stacks_f and stacks_f[0].pipe_schedule == "1f1b"
    for fg, ff in zip(stacks_g, stacks_f):
        for key in fg.PARAMS:
            a = numpy.asarray(wf_g.xla_step.params[fg.name][key])
            b = numpy.asarray(wf_f.xla_step.params[ff.name][key])
            assert numpy.allclose(a, b, atol=1e-5), \
                (key, numpy.abs(a - b).max())


def _permute_count(wf):
    import re
    hlo = wf.xla_step.lowered_epoch_hlo(optimized=True)
    return len(re.findall(r"collective-permute(?:-start)?\(", hlo))


def test_stacked_lm_1f1b_single_forward(monkeypatch):
    """The 1F1B fold (VERDICT r4 #1) runs ONE pipelined forward per
    train step: the loss tail folds into the fused schedule, so the
    epoch program carries exactly as many collective-permutes as
    GPipe — fused train schedule (permF+permB = 2) + eval forward (1)
    = 3. The legacy double-forward fallback (unfoldable tail) pays a
    4th: the un-stashed train forward's own permute chain."""
    tiny = {"n_train": 32, "n_valid": 32}
    spec = {"pipe": 4, "microbatches": 4, "schedule": "1f1b"}
    wf = _run_stacked_lm("xla", spec, epochs=1, loader_overrides=tiny)
    stack = next(f for f in wf.forwards
                 if isinstance(f, TransformerBlockStack))
    assert stack.pipe_tail is not None, \
        "token_dense -> EvaluatorLM tail must fold"
    assert [type(u).__name__ for u in stack.pipe_tail["units"]] == \
        ["TokenDense"]
    n_fold = _permute_count(wf)
    wf_g = _run_stacked_lm("xla", {"pipe": 4, "microbatches": 4},
                           epochs=1, loader_overrides=tiny)
    assert n_fold == _permute_count(wf_g) == 3
    # break the protocol -> the fold must disengage and the fallback
    # must pay the extra forward pass (one more permute chain)
    from veles.znicz_tpu.ops.attention import TokenDenseBase
    monkeypatch.setattr(TokenDenseBase, "tail_fwd", None)
    wf_fb = _run_stacked_lm("xla", spec, epochs=1,
                            loader_overrides=tiny)
    stack_fb = next(f for f in wf_fb.forwards
                    if isinstance(f, TransformerBlockStack))
    assert stack_fb.pipe_tail is None
    assert _permute_count(wf_fb) == 4


def test_stacked_lm_1f1b_schedule_trains_like_gpipe():
    """1F1B workflow histories track the single-device run. Gradient
    accumulation ORDER differs from GPipe (interleaved vs replay), so
    float non-associativity injects ~1e-7/step noise that SGD
    amplifies chaotically — short horizon + loose tolerance here; the
    strict check is the one-update leaf-for-leaf test above."""
    wf1 = _run_stacked_lm("xla", epochs=4)
    h1 = [e["validation"]["metric"] for e in wf1.decision.history]
    wf4 = _run_stacked_lm("xla", {"pipe": 4, "microbatches": 4,
                                  "schedule": "1f1b"}, epochs=4)
    h4 = [e["validation"]["metric"] for e in wf4.decision.history]
    assert numpy.allclose(h1, h4, atol=1e-2), (h1, h4)
    from veles.znicz_tpu import parallel
    parallel.assert_collectives(wf4.xla_step, ["collective-permute"])
    # composes with DP like GPipe does. 3e-2 (ISSUE 15 satellite,
    # PR-11 convention): 1F1B's interleaved accumulation stacks its
    # own reordering noise ON TOP of the DP all-reduce
    # thread-partitioning noise, and loaded 2-CPU containers amplify
    # both — 2e-2 still flaked there. Falsifiable: real schedule or
    # layout bugs diverge by O(1e-1)+ immediately (the strict
    # one-update check is the leaf-for-leaf test above); only this
    # DP-composed history comparison is widened.
    wf8 = _run_stacked_lm("xla", {"pipe": 4, "data": 2,
                                  "microbatches": 4,
                                  "schedule": "1f1b"}, epochs=4)
    h8 = [e["validation"]["metric"] for e in wf8.decision.history]
    assert numpy.allclose(h1, h8, atol=3e-2), (h1, h8)
    parallel.assert_collectives(
        wf8.xla_step, ["collective-permute", "all-reduce"])


def test_pp_1f1b_snapshot_restores_single_device(tmp_path):
    """A checkpoint written while the stacked layers were
    pipe-sharded (1F1B schedule) restores onto a single-device
    workflow — layout independence for PP too."""
    from veles.snapshotter import Snapshotter, load_snapshot

    wf = _run_stacked_lm("xla", {"pipe": 4, "microbatches": 4,
                                 "schedule": "1f1b"}, epochs=2)
    snap = Snapshotter(wf, name="snap", directory=str(tmp_path))
    snap.decision = wf.decision
    state = load_snapshot(snap.export_snapshot())
    wf1 = _run_stacked_lm("xla", seed=607, epochs=1)
    wf1.restore_state(state)
    stack = next(f for f in wf1.forwards
                 if isinstance(f, TransformerBlockStack))
    for key in stack.PARAMS:
        restored = wf1.xla_step.params[stack.name][key]
        assert numpy.array_equal(
            numpy.asarray(restored),
            numpy.asarray(state["params"][stack.name][key])), key
        assert len(restored.sharding.device_set) == 1


def test_1f1b_schedule_properties():
    """Static-schedule invariants: every stage finishes M forwards and
    M backwards; causality holds (consume strictly after neighbour
    production); peak stash per stage is min(M, P - s) — the 1F1B
    memory bound — and total ticks match GPipe's 2(M + P - 1)."""
    for P, M in [(2, 2), (2, 8), (4, 4), (4, 8), (3, 5)]:
        actions, fidx, bidx = PL.build_1f1b_schedule(P, M)
        T = actions.shape[0]
        assert T == 2 * (M + P - 1), (P, M, T)
        for s in range(P):
            f_ticks = {int(fidx[t, s]): t for t in range(T)
                       if actions[t, s] == 1}
            b_ticks = {int(bidx[t, s]): t for t in range(T)
                       if actions[t, s] == 2}
            assert sorted(f_ticks) == list(range(M))
            assert sorted(b_ticks) == list(range(M))
            # stash bound: live caches (fwd done, bwd not yet)
            peak = 0
            for t in range(T):
                live = sum(1 for m in range(M)
                           if f_ticks[m] <= t < b_ticks[m])
                peak = max(peak, live)
            assert peak <= min(M, max(P - s, 1)), (P, M, s, peak)
            if s > 0:
                prev_f = {int(fidx[t, s - 1]): t for t in range(T)
                          if actions[t, s - 1] == 1}
                for m in range(M):
                    assert f_ticks[m] > prev_f[m], (P, M, s, m)
            if s < P - 1:
                nxt_b = {int(bidx[t, s + 1]): t for t in range(T)
                         if actions[t, s + 1] == 2}
                for m in range(M):
                    assert b_ticks[m] > nxt_b[m], (P, M, s, m)


@pytest.mark.parametrize("axes,batch_axis,n_micro", [
    ({"pipe": 4}, None, 4),
    ({"pipe": 2}, None, 6),
    ({"data": 2, "pipe": 4}, "data", 2),
], ids=["pp4m4", "pp2m6", "dp2xpp4"])
def test_1f1b_matches_scan(axes, batch_axis, n_micro):
    """The interleaved 1F1B schedule is a pure re-ordering: y, dx,
    grads and loss must equal stack_fwd + err_fn + stack_bwd."""
    import jax
    import jax.numpy as jnp

    prng.seed_all(78)
    gen = prng.get("pp1f1b")
    L, B, S, D, H, heads = 4, 24, 6, 8, 16, 2
    mesh = _mesh(axes)
    x = gen.normal(0, 1.0, (B, S, D)).astype(numpy.float32)
    tgt = gen.normal(0, 1.0, (B, S, D)).astype(numpy.float32)
    params = {}
    shapes = {"weights": (L, D, 3 * D), "bias": (L, 3 * D),
              "weights_out": (L, D, D), "bias_out": (L, D),
              "ln1_g": (L, D), "ln1_b": (L, D),
              "ffn_w1": (L, D, H), "ffn_b1": (L, H),
              "ffn_w2": (L, H, D), "ffn_b2": (L, D),
              "ln2_g": (L, D), "ln2_b": (L, D)}
    for k, shp in shapes.items():
        if k.endswith("_g"):
            params[k] = numpy.ones(shp, numpy.float32)
        elif "bias" in k or k.endswith("_b"):
            params[k] = numpy.zeros(shp, numpy.float32)
        else:
            params[k] = gen.normal(0, 0.3, shp).astype(numpy.float32)

    def err_fn(y_mb, t_mb):
        # simple differentiable head: mse grad + scalar loss
        d = y_mb - t_mb
        return 2.0 * d / d.size, jnp.sum(d * d) / d.size

    y_ref, caches_ref = jax.jit(
        lambda p, xx: PL.stack_fwd(p, xx, heads, True, 1e-5))(params, x)
    derr_ref, loss_ref = err_fn(y_ref, jnp.asarray(tgt))
    dx_ref, g_ref = jax.jit(
        lambda p, c, e: PL.stack_bwd(p, c, e, heads, 1e-5))(
        params, caches_ref, derr_ref)

    y, dx, grads, loss = PL.pipeline_1f1b_step(
        params, x, tgt, err_fn, mesh, batch_axis=batch_axis,
        n_micro=n_micro, heads=heads, causal=True)
    assert numpy.allclose(numpy.asarray(y), numpy.asarray(y_ref),
                          atol=2e-5)
    # per-microbatch loss normalizes by the microbatch size; rescale
    dp = axes.get("data", 1)
    scale = n_micro * dp
    assert numpy.allclose(float(loss) / scale, float(loss_ref),
                          atol=1e-5)
    assert numpy.allclose(numpy.asarray(dx) / scale,
                          numpy.asarray(dx_ref), atol=2e-4)
    for k in g_ref:
        assert numpy.allclose(numpy.asarray(grads[k]) / scale,
                              numpy.asarray(g_ref[k]), atol=2e-4), k
