"""At-rest weight quantization for serving (ISSUE 14 tentpole piece
3): quant.py round-trip bounds, the eligibility policy, int8/fp8
forward parity against f32 on a golden archive (logit max-abs-diff +
top-1 agreement), the Prometheus ``veles_serving_forward_cache_bytes``
shrink (acceptance: int8 ≤ 55% of f32), hot-reload round-trip and
greedy-decode token parity."""

import json
import os
import shutil
import tempfile

import numpy
import pytest

import veles.prng as prng
from veles import telemetry
from veles.config import root
from veles.serving import quant
from veles.serving.quant import (MODES, QuantizedTensor, dense_params,
                                 quantize_tensor, quantize_tree)


@pytest.fixture(scope="module")
def mlp_archive(tmp_path_factory):
    """Untrained tiny MNIST MLP archive (initialize + export only —
    parity bounds price the quantization, not model quality)."""
    prng.seed_all(424)
    from veles.znicz_tpu.models import mnist
    saved = {k: root.mnist.loader.get(k)
             for k in ("minibatch_size", "n_train", "n_valid")}
    root.mnist.loader.update({"minibatch_size": 25, "n_train": 100,
                              "n_valid": 25})
    try:
        wf = mnist.create_workflow(name="WQuantMLP")
        wf.initialize(device="numpy")
        base = tmp_path_factory.mktemp("wquant")
        archive = str(base / "archive")
        wf.export_inference(archive)
        x = wf.loader.original_data.mem[:16].astype(numpy.float32)
        return {"archive": archive, "x": x}
    finally:
        root.mnist.loader.update(saved)


# -- codec-level -------------------------------------------------------


@pytest.mark.parametrize("mode", ("int8", "fp8"))
def test_round_trip_error_bounds(mode):
    prng.seed_all(11)
    gen = prng.get("wq")
    w = gen.normal(0, 0.3, (64, 48)).astype(numpy.float32)
    qt = quantize_tensor(w, mode)
    assert qt.shape == w.shape
    assert qt.nbytes < w.nbytes / 3.5     # ~1 byte/element + scales
    back = qt.dense(numpy)
    spread = w.max() - w.min()
    if mode == "int8":
        # affine 255-level grid: error ≤ half a step
        assert numpy.abs(back - w).max() <= spread / 255.0 * 0.51
    else:
        # e4m3: ~2 mantissa-bit relative error, elementwise
        rel = numpy.abs(back - w) / numpy.maximum(numpy.abs(w), 1e-3)
        assert rel.max() < 0.08, rel.max()


def test_constant_and_mode_edges():
    w = numpy.full((40, 40), 3.25, numpy.float32)
    for mode in ("int8", "fp8"):
        back = quantize_tensor(w, mode).dense(numpy)
        assert numpy.allclose(back, w, rtol=1e-2)
    # same-mode passthrough is the SAME object; cross-mode re-encodes
    qt = quantize_tensor(w, "int8")
    assert quantize_tensor(qt, "int8") is qt
    assert quantize_tensor(qt, "fp8").mode == "fp8"
    with pytest.raises(ValueError):
        quantize_tensor(w, "int4")


def test_tree_policy_skips_vectors():
    """Biases/LN vectors (ndim<2 or tiny) stay f32 — only
    matrix-shaped tensors carry the capacity bill."""
    tree = {"fc": {"weights": numpy.zeros((64, 64), numpy.float32),
                   "bias": numpy.zeros(64, numpy.float32),
                   "small": numpy.zeros((4, 4), numpy.float32)}}
    q = quantize_tree(tree, "int8")
    assert isinstance(q["fc"]["weights"], QuantizedTensor)
    assert isinstance(q["fc"]["bias"], numpy.ndarray)
    assert isinstance(q["fc"]["small"], numpy.ndarray)
    assert quantize_tree(tree, "none") is tree
    with pytest.raises(ValueError):
        quantize_tree(tree, "bf16")
    dense = dense_params(numpy, q["fc"])
    assert all(isinstance(v, numpy.ndarray) for v in dense.values())
    # identity-cheap when nothing is quantized
    assert dense_params(numpy, tree["fc"]) is tree["fc"]


def test_quantized_tree_survives_jit_as_pytree():
    """The registered pytree node: device_put + jit thread the
    payload/scale as runtime leaves, so a scale change does NOT
    retrace (the hot-reload-keeps-programs contract)."""
    import jax
    import jax.numpy as jnp
    w = numpy.linspace(-1, 1, 64 * 32).reshape(64, 32) \
        .astype(numpy.float32)
    qt = quantize_tensor(w, "int8")
    traces = []

    @jax.jit
    def dot(q, x):
        traces.append(1)
        return jnp.matmul(x, q.dense(jnp))

    x = numpy.ones((2, 64), numpy.float32)
    y1 = dot(jax.device_put(qt), x)
    assert numpy.allclose(numpy.asarray(y1), x @ qt.dense(numpy),
                          atol=1e-5)
    qt2 = quantize_tensor(w * 2.0, "int8")       # new scale, same shape
    y2 = dot(jax.device_put(qt2), x)
    assert len(traces) == 1, "scale change must not retrace"
    assert numpy.allclose(numpy.asarray(y2), 2 * numpy.asarray(y1),
                          atol=1e-4)


# -- serving parity + accounting ---------------------------------------


def _cache_gauge(name):
    return telemetry.get_registry().gauge(
        "veles_serving_forward_cache_bytes",
        labels=("model",)).labels(name).value


@pytest.mark.parametrize("mode", ("int8", "fp8"))
def test_forward_parity_and_gauge_shrink(mlp_archive, mode):
    """THE acceptance pins: quantized logits within bounds of f32
    (max-abs-diff + full top-1 agreement on the golden archive), and
    the Prometheus forward-cache gauge at ≤ 55% of the f32 figure."""
    from veles.serving import ModelRegistry
    x = mlp_archive["x"]
    out, cache = {}, {}
    for m in ("none", mode):
        reg = ModelRegistry(backend="jit", max_batch=16,
                            quantize_weights=m)
        try:
            entry = reg.load("golden", mlp_archive["archive"])
            y, _ = entry.engine.predict(x)
            out[m] = numpy.asarray(y)
            cache[m] = _cache_gauge("golden")
            assert cache[m] == entry.cache_bytes()
        finally:
            reg.close()
    diff = numpy.abs(out[mode] - out["none"]).max()
    assert diff < 2e-2, diff              # post-softmax probabilities
    # top-1 agreement wherever f32 has a REAL margin: a row whose
    # top-2 gap exceeds twice the observed perturbation cannot flip;
    # near-tie rows on this untrained archive legitimately may
    top2 = numpy.sort(out["none"], axis=1)[:, -2:]
    margin = top2[:, 1] - top2[:, 0]
    strong = margin > 2 * diff
    agree = out[mode].argmax(1) == out["none"].argmax(1)
    assert strong.any()
    assert agree[strong].all(), (margin, agree)
    ratio = cache[mode] / cache["none"]
    assert ratio <= 0.55, ratio


def test_quantized_hot_reload_round_trip(mlp_archive, tmp_path):
    """Reload under int8: version bumps, compiled programs survive,
    outputs track the new weights, and the at-rest tree STAYS
    quantized (a refresh must not silently fatten the cache back to
    f32)."""
    from veles.serving import ModelRegistry
    src = str(tmp_path / "archive")
    shutil.copytree(mlp_archive["archive"], src)
    reg = ModelRegistry(backend="jit", max_batch=8,
                        quantize_weights="int8")
    try:
        entry = reg.load("m", src, warmup=True)
        buckets = list(entry.engine.compiled_buckets)
        bytes_before = entry.cache_bytes()
        before = entry.predict(mlp_archive["x"][:2])
        with open(os.path.join(src, "contents.json")) as f:
            head = [u for u in json.load(f)["units"]
                    if u["type"] == "softmax"][0]
        for key in ("weights", "bias"):
            path = os.path.join(src, head[key])
            numpy.save(path, numpy.zeros_like(numpy.load(path)))
        entry2 = reg.reload("m")
        assert entry2 is entry and entry.version == 2
        assert entry.engine.compiled_buckets == buckets
        after = entry.predict(mlp_archive["x"][:2])
        assert numpy.abs(after - before).max() > 1e-4
        numpy.testing.assert_allclose(after, 0.1, atol=1e-2)
        assert any(
            isinstance(v, QuantizedTensor)
            for tree in entry.model.params.values()
            for v in tree.values())
        assert entry.cache_bytes() <= bytes_before
    finally:
        reg.close()


def test_decode_greedy_token_parity():
    """int8 decode through the continuous batcher: greedy tokens match
    the f32 decode on the tiny LM wherever f32 has a REAL top-2 margin
    (near-tie steps on an untrained archive may legitimately flip —
    the same margin gate the forward-parity test uses; a blanket
    token-for-token equality would be a cross-platform flake), and the
    KV-pool-inclusive cache gauge still shrinks."""
    from veles.serving import ModelRegistry
    from veles.znicz_tpu.models import transformer_lm
    prng.seed_all(99)
    saved_loader = root.lm.loader.to_dict()
    saved_model = root.lm.model.to_dict()
    root.lm.loader.update({"minibatch_size": 8, "n_train": 64,
                           "n_valid": 16, "seq_len": 16, "vocab": 32,
                           "max_period": 8})
    root.lm.model.update({"dim": 64, "heads": 4, "layers": 2,
                          "ffn_hidden": 128, "moe_experts": 0,
                          "attn_block": None, "attn_impl": None,
                          "stacked": False})
    prompt, n_new = [1, 2, 3], 8
    try:
        wf = transformer_lm.create_workflow(name="WQuantLM")
        wf.initialize(device="numpy")
        with tempfile.TemporaryDirectory() as tmp:
            wf.export_inference(tmp)
            toks, logits, cache = {}, {}, {}
            for mode in ("none", "int8"):
                reg = ModelRegistry(backend="jit", max_batch=8,
                                    quantize_weights=mode,
                                    decode_slots=2, decode_max_len=32)
                try:
                    entry = reg.load("lm", tmp)
                    dec = reg.decoder("lm")
                    toks[mode] = dec.generate(prompt,
                                              max_tokens=n_new,
                                              wait_s=300)
                    cache[mode] = _cache_gauge("lm")
                    # teacher-forced per-step logits along the F32
                    # greedy chain ("none" runs first): the margin
                    # gate below needs both modes' view of the SAME
                    # contexts, independent of where either chain
                    # wanders after a near-tie flip
                    chain = prompt + toks["none"]
                    seq = root.lm.loader.seq_len
                    rows = []
                    for i in range(n_new):
                        row = chain[:len(prompt) + i]
                        rows.append(row + [0] * (seq - len(row)))
                    y, _ = entry.engine.predict(
                        numpy.asarray(rows, numpy.float32))
                    y = numpy.asarray(y)
                    logits[mode] = numpy.stack(
                        [y[i, len(prompt) + i - 1]
                         for i in range(n_new)])
                finally:
                    reg.close()
            assert cache["int8"] < cache["none"], cache
            diff = numpy.abs(logits["int8"] - logits["none"]).max()
            top2 = numpy.sort(logits["none"], axis=1)[:, -2:]
            margin = top2[:, 1] - top2[:, 0]
            # 2x the observed perturbation plus slack for the decode
            # plane's KV-cached programs reducing in another order
            strong = margin > 2 * diff + 1e-3
            assert strong.any(), (margin, diff)
            agree = (numpy.asarray(toks["int8"])
                     == numpy.asarray(toks["none"]))
            # the chains share context only until the first flip, so
            # the gate applies to the strong PREFIX: a divergence at a
            # weak step releases everything after it
            for i in range(n_new):
                if not strong[i]:
                    break
                assert agree[i], (i, toks, margin, diff)
    finally:
        root.lm.loader.update(saved_loader)
        root.lm.model.update(saved_model)


def test_registry_rejects_unknown_mode():
    from veles.serving import ModelRegistry
    with pytest.raises(ValueError):
        ModelRegistry(quantize_weights="int4")
    from veles.serving.engine import InferenceEngine
    with pytest.raises(ValueError):
        InferenceEngine(None, backend="numpy", quantize="fp16")
    assert MODES == ("none", "int8", "fp8")
