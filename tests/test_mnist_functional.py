"""Functional test: the MNIST sample converges, and the XLA backend
reaches the numpy oracle's accuracy (BASELINE.json north star:
"samples/MNIST converging to the same accuracy as the numpy backend";
SURVEY.md §4 "Functional tests" — fixed seeds, per-epoch metrics)."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root


def build_and_run(backend):
    prng.seed_all(1337)
    # fresh generator registry state for exact reproducibility
    from veles.znicz_tpu.models import mnist
    root.mnist.decision.max_epochs = 3
    wf = mnist.create_workflow(name="MnistTest_%s" % backend)
    wf.initialize(device=backend)
    wf.run()
    return wf


def final_valid_error(wf):
    last = wf.decision.history[-1]
    return last["validation"]["metric"]


@pytest.fixture(scope="module")
def numpy_wf():
    return build_and_run("numpy")


def test_numpy_converges(numpy_wf):
    err = final_valid_error(numpy_wf)
    first = numpy_wf.decision.history[0]["validation"]["metric"]
    assert err < 0.15, "validation error %.3f too high" % err
    assert err <= first, "no improvement over training"


def test_xla_matches_numpy(numpy_wf):
    wf = build_and_run("cpu")
    err_np = final_valid_error(numpy_wf)
    err_x = final_valid_error(wf)
    assert abs(err_np - err_x) < 0.02, (err_np, err_x)
    # weights synced back to host after run(): finite and same shape
    w = wf.forwards[0].weights.map_read().mem
    assert numpy.isfinite(w).all()


def test_deterministic_rerun(numpy_wf):
    """Fixed-seed functional determinism (reference contract, §4)."""
    wf2 = build_and_run("numpy")
    h1 = [e["validation"]["metric"] for e in numpy_wf.decision.history]
    h2 = [e["validation"]["metric"] for e in wf2.decision.history]
    assert h1 == h2
