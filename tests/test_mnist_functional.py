"""Functional test: the MNIST sample converges, and the XLA backend
reaches the numpy oracle's accuracy (BASELINE.json north star:
"samples/MNIST converging to the same accuracy as the numpy backend";
SURVEY.md §4 "Functional tests" — fixed seeds, per-epoch metrics)."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root


def build_and_run(backend):
    prng.seed_all(1337)
    # fresh generator registry state for exact reproducibility
    from veles.znicz_tpu.models import mnist
    root.mnist.decision.max_epochs = 3
    wf = mnist.create_workflow(name="MnistTest_%s" % backend)
    wf.initialize(device=backend)
    wf.run()
    return wf


def final_valid_error(wf):
    last = wf.decision.history[-1]
    return last["validation"]["metric"]


@pytest.fixture(scope="module")
def numpy_wf():
    return build_and_run("numpy")


def test_numpy_converges(numpy_wf):
    err = final_valid_error(numpy_wf)
    first = numpy_wf.decision.history[0]["validation"]["metric"]
    assert err < 0.15, "validation error %.3f too high" % err
    assert err <= first, "no improvement over training"


def test_xla_matches_numpy(numpy_wf):
    wf = build_and_run("cpu")
    err_np = final_valid_error(numpy_wf)
    err_x = final_valid_error(wf)
    assert abs(err_np - err_x) < 0.02, (err_np, err_x)
    # weights synced back to host after run(): finite and same shape
    w = wf.forwards[0].weights.map_read().mem
    assert numpy.isfinite(w).all()


@pytest.mark.parametrize("backend", ["numpy", "cpu"])
def test_zerofiller_pins_weights(backend):
    """ZeroFiller keeps masked weight entries at zero on BOTH backends
    (ADVICE r1: the XLA path used to ignore the host-side mask)."""
    from veles.znicz_tpu.ops.cutter import ZeroFiller

    prng.seed_all(11)
    from veles.znicz_tpu.models import mnist
    saved = {k: getattr(root.mnist.loader, k, None)
             for k in ("minibatch_size", "n_train", "n_valid")}
    root.mnist.loader.update({"minibatch_size": 20,
                              "n_train": 100, "n_valid": 40})
    root.mnist.decision.max_epochs = 2
    try:
        wf = mnist.create_workflow(name="ZeroFill_%s" % backend)
        target = wf.forwards[0]
        zf = ZeroFiller(wf, target=target, name="zerofiller")
        # run right after the last GD unit, before looping back
        zf.link_from(wf.gds[0])
        wf.initialize(device=backend)
        mask = numpy.ones_like(target.weights.mem)
        mask[::2, :] = 0.0
        zf.mask.map_write()
        zf.mask.mem[...] = mask
        wf.run()
        w = target.weights.map_read().mem
    finally:
        root.mnist.loader.update(
            {k: v for k, v in saved.items() if v is not None})
    assert numpy.all(w[::2, :] == 0.0), "masked entries drifted"
    assert numpy.any(w[1::2, :] != 0.0), "unmasked entries all zero?"


def test_chunked_dispatch_matches_per_epoch():
    """Multi-epoch dispatch (several epochs fused into one XLA program,
    one metric fetch per chunk) must be semantically identical to
    per-epoch dispatch: same shuffles, same PRNG keys, same history."""
    def run(chunk):
        prng.seed_all(321)
        from veles.znicz_tpu.models import mnist
        saved = {k: getattr(root.mnist.loader, k, None)
                 for k in ("minibatch_size", "n_train", "n_valid")}
        root.mnist.loader.update({"minibatch_size": 25,
                                  "n_train": 200, "n_valid": 50})
        root.mnist.decision.max_epochs = 4
        try:
            wf = mnist.create_workflow(name="Chunk%s" % chunk)
            wf.initialize(device="cpu")
            wf.xla_step.epochs_per_dispatch = chunk
            wf.run()
        finally:
            root.mnist.loader.update(
                {k: v for k, v in saved.items() if v is not None})
        return wf.decision.history

    h1 = run(1)
    h4 = run(4)
    assert len(h1) == len(h4) == 4
    for a, b in zip(h1, h4):
        assert a["validation"]["metric"] == b["validation"]["metric"], \
            (a, b)
        assert abs(a["train"]["loss"] - b["train"]["loss"]) < 1e-5


def test_forced_chunk_clipped_by_stop_criteria():
    """A forced epochs_per_dispatch must still respect max_epochs:
    params may never advance past the decision's stop point."""
    prng.seed_all(77)
    from veles.znicz_tpu.models import mnist
    saved = {k: getattr(root.mnist.loader, k, None)
             for k in ("minibatch_size", "n_train", "n_valid")}
    root.mnist.loader.update({"minibatch_size": 25,
                              "n_train": 100, "n_valid": 25})
    root.mnist.decision.max_epochs = 3
    try:
        wf = mnist.create_workflow(name="ChunkClip")
        wf.initialize(device="cpu")
        wf.xla_step.epochs_per_dispatch = 8   # > max_epochs
        wf.run()
    finally:
        root.mnist.loader.update(
            {k: v for k, v in saved.items() if v is not None})
    assert len(wf.decision.history) == 3
    # the loader never started an epoch past the stop point, so no
    # trained-past-the-end params exist
    assert wf.loader.epoch_number <= 3


def test_deterministic_rerun(numpy_wf):
    """Fixed-seed functional determinism (reference contract, §4)."""
    wf2 = build_and_run("numpy")
    h1 = [e["validation"]["metric"] for e in numpy_wf.decision.history]
    h2 = [e["validation"]["metric"] for e in wf2.decision.history]
    assert h1 == h2
