"""MnistAE functional test (SURVEY.md §2.8 row 6) + evaluator metric
parity (confusion matrix / max-error tracking on BOTH backends)."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root


def build_and_run(backend, name):
    prng.seed_all(7)
    from veles.znicz_tpu.models import mnist_ae
    root.mnist_ae.loader.n_train = 400
    root.mnist_ae.loader.n_valid = 100
    root.mnist_ae.loader.minibatch_size = 50
    root.mnist_ae.decision.max_epochs = 3
    wf = mnist_ae.create_workflow(name=name)
    wf.initialize(device=backend)
    wf.run()
    return wf


@pytest.fixture(scope="module")
def numpy_wf():
    return build_and_run("numpy", "AENumpy")


def test_ae_reconstruction_improves(numpy_wf):
    hist = [h["validation"]["metric"]
            for h in numpy_wf.decision.history]
    assert hist[-1] < hist[0], hist


def test_ae_xla_matches_numpy(numpy_wf):
    wf = build_and_run("cpu", "AEXLA")
    mse_np = numpy_wf.decision.history[-1]["validation"]["metric"]
    mse_x = wf.decision.history[-1]["validation"]["metric"]
    assert abs(mse_np - mse_x) < max(0.15 * mse_np, 1e-3), \
        (mse_np, mse_x)


def test_ae_max_err_tracked(numpy_wf):
    ev = numpy_wf.evaluator
    assert ev.max_err > 0.0
    assert 0 <= ev.max_err_idx < numpy_wf.loader.max_minibatch_size


def test_video_ae_reconstruction():
    """VideoAE (SURVEY.md §2.8 row 6): frame AE on held-out clips,
    both backends agree."""
    prng.seed_all(21)
    from veles.znicz_tpu.models import video_ae
    root.video_ae.loader.n_clips = 12
    root.video_ae.decision.max_epochs = 3
    wf = video_ae.create_workflow(name="VAENumpy")
    wf.initialize(device="numpy")
    wf.run()
    hist = [h["validation"]["metric"] for h in wf.decision.history]
    assert hist[-1] < hist[0], hist
    prng.seed_all(21)
    wf2 = video_ae.create_workflow(name="VAEXLA")
    wf2.initialize(device="cpu")
    wf2.run()
    h2 = [h["validation"]["metric"] for h in wf2.decision.history]
    assert abs(h2[-1] - hist[-1]) < max(0.15 * hist[-1], 1e-3), \
        (hist, h2)


# -- evaluator parity: confusion matrix + max-error on the traced path


def _run_mnist(backend, name):
    prng.seed_all(31)
    from veles.znicz_tpu.models import mnist
    from veles.znicz_tpu.ops.evaluator import EvaluatorSoftmax
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    saved_epochs = root.mnist.decision.get("max_epochs")
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 100
    root.mnist.loader.minibatch_size = 50
    root.mnist.decision.max_epochs = 2

    def make_eval(wf, last):
        ev = EvaluatorSoftmax(wf, name="evaluator",
                              compute_confusion=True)
        ev.link_attrs(last, ("input", "output"), "max_idx")
        ev.link_attrs(wf.loader,
                      ("labels", "minibatch_labels"),
                      ("batch_size", "minibatch_size"))
        return ev

    # the standard mnist graph, but with confusion enabled via the
    # evaluator factory hook
    from veles.znicz_tpu.standard_workflow import StandardWorkflow
    wf = StandardWorkflow(
        None, name=name, layers=root.mnist.layers,
        loader_factory=lambda w: mnist.MnistLoader(
            w, name="loader",
            minibatch_size=root.mnist.loader.minibatch_size),
        evaluator_factory=make_eval,
        decision_config=root.mnist.decision.to_dict())
    try:
        wf.initialize(device=backend)
        wf.run()
    finally:
        root.mnist.loader.update(saved)
        root.mnist.decision.max_epochs = saved_epochs
    return wf


def test_confusion_matrix_parity():
    wf_np = _run_mnist("numpy", "EvNumpy")
    wf_x = _run_mnist("cpu", "EvXLA")
    m_np = wf_np.evaluator.confusion_matrix.map_read().mem
    m_x = wf_x.evaluator.confusion_matrix.map_read().mem
    # both paths accumulated every serve of every epoch
    assert m_np.sum() == m_x.sum() > 0
    # per-cell agreement: same seeds, same serve order => identical
    # up to fp round-off in argmax ties (none expected on this data)
    assert numpy.array_equal(m_np, m_x), (m_np, m_x)
    assert wf_np.evaluator.max_err > 0
    assert wf_x.evaluator.max_err > 0
