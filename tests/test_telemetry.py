"""Unified telemetry core (ISSUE 3): registry instruments, label
handling, Prometheus exposition, span tracing, the serving JSON view
over the registry, and cluster counter aggregation.

Every test runs under a fresh scoped registry (autouse fixture in
conftest.py) — the isolation itself is regression-tested here too.
"""

import json
import os
import re
import subprocess
import sys
import urllib.request

import numpy
import pytest

from veles import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- instruments -------------------------------------------------------


def test_counter_labels_and_totals():
    fam = telemetry.counter("t_requests_total", "test", ("model",))
    fam.labels("a").inc()
    fam.labels("a").inc(2)
    fam.labels("b").inc()
    assert fam.labels("a").value == 3
    assert fam.labels(model="b").value == 1
    reg = telemetry.get_registry()
    assert reg.counter_total("t_requests_total") == 4
    assert reg.counter_total("t_requests_total", model="a") == 3
    assert reg.counter_total("no_such_total") == 0.0
    # label arity/name validation
    with pytest.raises(ValueError):
        fam.labels("a", "b")
    with pytest.raises(ValueError):
        fam.labels(nope="a")
    # label-less family acts as its own child
    plain = telemetry.counter("t_plain_total")
    plain.inc(5)
    assert plain.value == 5
    with pytest.raises(ValueError):
        plain.inc(-1)              # counters only go up
    # a labelled family refuses direct use
    with pytest.raises(ValueError):
        fam.inc()
    # same name, different kind -> loud failure
    with pytest.raises(ValueError):
        telemetry.gauge("t_requests_total")


def test_absorb_before_declare_adopts_label_schema():
    """Regression: a master may absorb a slave's counters BEFORE the
    local instrumented path declares the family with labels — the
    later declared schema must be adopted, not rejected."""
    reg = telemetry.get_registry()
    reg.absorb_counters(
        {("t_adopt_total", (("cls", "train"),)): 5.0},
        extra_labels=(("slave", "1"),))
    fam = telemetry.counter("t_adopt_total", "declared later",
                            ("loader", "cls"))
    fam.labels("ld", "train").inc(2)      # must not raise
    assert reg.counter_total("t_adopt_total") == 7
    assert reg.counter_total("t_adopt_total", slave="1") == 5


def test_gauge_set_inc_dec():
    g = telemetry.gauge("t_depth")
    g.set(7)
    g.inc(2)
    g.dec()
    assert g.value == 8


def test_histogram_percentiles_vs_numpy(rng):
    h = telemetry.histogram("t_lat_seconds", "test")
    vals = rng.random(1500)
    for v in vals:
        h.observe(float(v))
    assert h.count == 1500
    assert abs(h.sum - vals.sum()) < 1e-6
    lat = numpy.sort(vals)
    # the exact index convention the serving metrics always used
    assert h.percentile(0.5) == lat[min(len(lat) - 1,
                                        int(len(lat) * 0.5))]
    assert h.percentile(0.99) == lat[min(len(lat) - 1,
                                         int(len(lat) * 0.99))]
    # and numerically equivalent to numpy's percentiles on this size
    assert abs(h.percentile(0.5)
               - numpy.percentile(vals, 50)) < 0.01
    assert abs(h.percentile(0.99)
               - numpy.percentile(vals, 99)) < 0.01
    assert telemetry.histogram("t_empty_seconds").percentile(0.5) \
        is None


# -- test isolation (the autouse scoped-registry fixture) --------------
# Both directions: whichever runs first increments, the other must
# still see a virgin registry.


def test_registry_isolation_leg_a():
    assert telemetry.get_registry().counter_total(
        "t_isolation_total") == 0
    telemetry.counter("t_isolation_total").inc(41)


def test_registry_isolation_leg_b():
    assert telemetry.get_registry().counter_total(
        "t_isolation_total") == 0
    telemetry.counter("t_isolation_total").inc(17)


# -- Prometheus exposition ---------------------------------------------

_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r' (\+Inf|-?[0-9.eE+-]+)$')


def test_prometheus_exposition_parses():
    telemetry.counter("t_c_total", "counter help",
                      ("model",)).labels('we"ird\\na<me').inc(2)
    telemetry.gauge("t_g", "gauge help").set(1.5)
    h = telemetry.histogram("t_h_seconds", "hist help")
    for v in (0.0001, 0.003, 0.04, 2.0):
        h.observe(v)
    text = telemetry.get_registry().render_prometheus()
    lines = text.strip().split("\n")
    # TYPE lines present and correct
    assert "# TYPE t_c_total counter" in lines
    assert "# TYPE t_g gauge" in lines
    assert "# TYPE t_h_seconds histogram" in lines
    # every sample line parses
    samples = [l for l in lines if not l.startswith("#")]
    for line in samples:
        assert _SAMPLE_RE.match(line), "unparseable: %r" % line
    # histogram contract: cumulative buckets, +Inf == count
    buckets = [l for l in samples
               if l.startswith("t_h_seconds_bucket")]
    counts = [int(l.rsplit(" ", 1)[1]) for l in buckets]
    assert counts == sorted(counts), "buckets must be cumulative"
    assert buckets[-1].startswith('t_h_seconds_bucket{le="+Inf"}')
    assert counts[-1] == 4
    count_line = [l for l in samples
                  if l.startswith("t_h_seconds_count")][0]
    assert count_line.endswith(" 4")
    assert any(l.startswith("t_h_seconds_sum") for l in samples)
    # label escaping survived
    assert 't_c_total{model="we\\"ird\\\\na<me"} 2' in samples


def test_prometheus_escapes_label_values_and_help():
    """Satellite regression (ISSUE 6): label values carrying every
    escapable character (backslash, double quote, newline) and HELP
    text carrying backslash/newline must render per the exposition
    format — one raw ``"`` in a model name used to be the difference
    between a scrape and a parser error."""
    fam = telemetry.counter(
        "t_esc_total", 'help with \\ backslash\nand newline',
        ("model",))
    fam.labels('say "hi"\\now\n!').inc(3)
    text = telemetry.get_registry().render_prometheus()
    lines = text.strip().split("\n")
    help_line = [l for l in lines
                 if l.startswith("# HELP t_esc_total")][0]
    assert help_line == ("# HELP t_esc_total help with \\\\ "
                         "backslash\\nand newline")
    sample = [l for l in lines if l.startswith("t_esc_total{")][0]
    assert sample == \
        't_esc_total{model="say \\"hi\\"\\\\now\\n!"} 3'
    assert _SAMPLE_RE.match(sample), sample
    # no raw newline leaked into any line
    assert all("\n" not in l for l in lines)


# -- span tracer -------------------------------------------------------


def test_traceparent_round_trip_and_rejects_garbage():
    ctx = telemetry.TraceContext.new()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    back = telemetry.TraceContext.from_traceparent(
        ctx.to_traceparent())
    assert (back.trace_id, back.span_id) == (ctx.trace_id, ctx.span_id)
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.parent_id == ctx.span_id
    assert child.span_id != ctx.span_id
    for bad in (None, "", "xx", "00-short-ff-01",
                "00-%s-%s-01" % ("g" * 32, "f" * 16)):
        assert telemetry.TraceContext.from_traceparent(bad) is None
    assert telemetry.TraceContext.from_wire("not-a-dict") is None
    wire = telemetry.TraceContext.from_wire(ctx.to_wire())
    assert wire.trace_id == ctx.trace_id


def test_tracer_drop_counter_exported():
    """Satellite: full-buffer drops are a scraped counter, not just a
    note buried in the dump's otherData — a scrape can now SEE that a
    trace window is incomplete."""
    tr = telemetry.Tracer()
    tr.max_events = 3
    tr.start()
    for _ in range(5):
        tr.add_complete("e", 0.0, 0.0)
    reg = telemetry.get_registry()
    assert reg.counter_total(
        "veles_trace_dropped_events_total") == 2
    assert len(tr.events()) == 3


def test_flight_recorder_records_while_disabled(tmp_path):
    """The tentpole's postmortem contract: with the tracer NEVER
    enabled, spans still land in the bounded ring and flight_doc()
    serves a parseable Perfetto window of them."""
    assert not telemetry.tracer.enabled
    assert telemetry.tracer.active          # flight is on by default
    with telemetry.span("bg.work", step=1):
        pass
    assert telemetry.tracer.events() == []  # full buffer untouched
    doc = telemetry.tracer.flight_doc()
    names = [e["name"] for e in doc["traceEvents"]
             if e["ph"] == "X"]
    assert "bg.work" in names
    # the document round-trips as JSON (what /debug/trace serves)
    doc2 = json.loads(json.dumps(doc))
    assert doc2["otherData"]["spans"] == str(len(names))
    # a zero-width window serves nothing
    assert [e for e in telemetry.tracer.flight_doc(0)["traceEvents"]
            if e["ph"] == "X"] in ([], )


def test_record_event_log_and_absorb_remote():
    telemetry.record_event("reconnect", name="slave-1", attempt=2)
    telemetry.record_event("checkpoint_written", name="x", slot="best")
    events = telemetry.tracer.recent_events()
    assert [e["event"] for e in events[-2:]] == \
        ["reconnect", "checkpoint_written"]
    assert telemetry.tracer.recent_events(limit=1)[0]["event"] == \
        "checkpoint_written"
    # remote spans merge wall-anchored, with a named track; malformed
    # entries are skipped, not fatal
    import time as _time
    n = telemetry.tracer.absorb_remote([
        {"name": "slave.compute", "wall": _time.time(), "dur": 0.01,
         "pid": 4242, "tid": 7, "args": {"trace_id": "t" * 32}},
        {"garbage": True},
    ], process_name="slave:far")
    assert n == 1
    doc = telemetry.tracer.flight_doc()
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert any(e["name"] == "slave.compute" and e["pid"] == 4242
               for e in spans)
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert any(e["args"]["name"] == "slave:far" for e in meta)


def test_debug_endpoints_on_web_status_and_cli(tmp_path):
    """GET /debug/trace and /debug/events on a live dashboard return
    parseable payloads, and ``velescli debug`` drives them end to
    end (table + saved Perfetto file; exit 2 on a dead endpoint)."""
    from veles.web_status import WebStatus
    from veles.__main__ import debug_main
    with telemetry.span("live.span", job=1):
        pass
    telemetry.record_event("fault", kind="drops", n=1)
    ws = WebStatus(port=0)
    try:
        base = "http://127.0.0.1:%d" % ws.port
        with urllib.request.urlopen(base + "/debug/trace?window=60",
                                    timeout=10) as resp:
            doc = json.load(resp)
        assert doc["otherData"]["window_s"] == "60"
        assert any(e["name"] == "live.span"
                   for e in doc["traceEvents"] if e["ph"] == "X")
        with urllib.request.urlopen(base + "/debug/events",
                                    timeout=10) as resp:
            events = json.load(resp)["events"]
        assert any(e["event"] == "fault" for e in events)
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(base + "/debug/nope", timeout=10)
        assert err.value.code == 404
        out = str(tmp_path / "window.json")
        assert debug_main([base, "--trace-out", out]) == 0
        with open(out) as f:
            saved = json.load(f)
        assert any(e["name"] == "live.span"
                   for e in saved["traceEvents"] if e["ph"] == "X")
    finally:
        ws.close()
    assert debug_main(["http://127.0.0.1:1"]) == 2


def test_debug_cli_exits_2_on_misshaped_200():
    """A 200 answer that is not the /debug payload shape (array
    instead of object, wrong value types) exits 2 — never a
    traceback (same contract as the checkpoints CLI)."""
    import http.server
    import threading
    from veles.__main__ import debug_main

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = b'["not", "the", "shape"]'
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        rc = debug_main(["http://127.0.0.1:%d"
                         % srv.server_address[1]])
        assert rc == 2
    finally:
        srv.shutdown()
        srv.server_close()


def test_flight_doc_reports_ring_coverage():
    """Under span pressure the bounded ring holds less than the
    requested window; flight_doc must say so (covered_s +
    ring_evicted) instead of silently truncating."""
    import time as _time
    tr = telemetry.Tracer()
    tr.flight_max_events = 8
    tr._ring = __import__("collections").deque(maxlen=8)
    now = _time.perf_counter()
    for i in range(20):
        tr.add_complete("e%d" % (i % 2), now + i * 1e-6, 0.0)
    doc = tr.flight_doc(window=600)
    other = doc["otherData"]
    assert other["ring_evicted"] == "12"
    assert int(other["spans"]) == 8
    assert float(other["covered_s"]) <= 600.0


def test_trace_file_is_valid_chrome_trace(tmp_path):
    telemetry.tracer.start()
    with telemetry.span("outer", unit="conv1"):
        with telemetry.span("inner"):
            pass
    path = str(tmp_path / "t.json")
    telemetry.tracer.dump(path)
    telemetry.tracer.stop()
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert len(events) == 2
    by_name = {e["name"]: e for e in events}
    assert set(by_name) == {"outer", "inner"}
    for e in events:
        assert e["ph"] == "X"
        assert isinstance(e["ts"], float) and e["ts"] >= 0
        assert isinstance(e["dur"], float) and e["dur"] >= 0
        assert "pid" in e and "tid" in e
    assert by_name["outer"]["args"] == {"unit": "conv1"}
    # inner nests inside outer on the timeline
    o, i = by_name["outer"], by_name["inner"]
    assert o["ts"] <= i["ts"]
    assert i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 1e-3


def test_disabled_tracer_records_nothing():
    assert not telemetry.tracer.enabled
    with telemetry.span("ghost"):
        pass
    telemetry.tracer.add_complete("ghost2", 0.0, 1.0)
    assert telemetry.tracer.events() == []


# -- unit runtime instrumentation --------------------------------------


def test_unit_run_histogram_and_spans():
    from veles.units import Unit
    from veles.workflow import Workflow

    class Work(Unit):
        def run(self):
            pass

    wf = Workflow(None, name="TeleWF")
    u = Work(wf, name="worker")
    u.link_from(wf.start_point)
    wf.end_point.link_from(u)
    telemetry.tracer.start()
    wf.run()
    telemetry.tracer.stop()
    reg = telemetry.get_registry()
    text = reg.render_prometheus()
    assert 'veles_unit_run_seconds_count{unit="worker"} 1' in text
    assert u.run_calls == 1 and u.run_time >= 0  # old view survives
    names = {e["name"] for e in telemetry.tracer.events()}
    assert "worker.run" in names
    assert "workflow.run" in names


def test_loader_counters_on_a_real_run():
    import veles.prng as prng
    from veles.config import root
    from veles.znicz_tpu.models import mnist
    prng.seed_all(404)
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    saved_epochs = root.mnist.decision.get("max_epochs")
    root.mnist.loader.update(
        {"n_train": 200, "n_valid": 80, "minibatch_size": 40})
    root.mnist.decision.max_epochs = 2
    try:
        wf = mnist.create_workflow(name="TeleMnist")
        wf.initialize(device="numpy")
        wf.run()
    finally:
        root.mnist.loader.update(saved)
        root.mnist.decision.max_epochs = saved_epochs
    reg = telemetry.get_registry()
    loader = wf.loader.name
    # 2 epochs × 200 train samples
    assert reg.counter_total("veles_loader_samples_total",
                             loader=loader, cls="train") == 400
    assert reg.counter_total("veles_loader_samples_total",
                             loader=loader, cls="validation") == 160
    assert reg.counter_total("veles_loader_minibatches_total",
                             loader=loader, cls="train") == 10
    assert reg.counter_total("veles_loader_epochs_total",
                             loader=loader) >= 1
    # per-unit histograms cover the hot units
    text = reg.render_prometheus()
    assert 'veles_unit_run_seconds_count{unit="%s"}' % loader in text


def test_xla_compile_and_dispatch_metrics():
    import veles.prng as prng
    from veles.config import root
    from veles.znicz_tpu.models import mnist
    prng.seed_all(405)
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    saved_epochs = root.mnist.decision.get("max_epochs")
    root.mnist.loader.update(
        {"n_train": 64, "n_valid": 32, "minibatch_size": 16})
    root.mnist.decision.max_epochs = 2
    try:
        wf = mnist.create_workflow(name="TeleXla")
        wf.initialize(device="cpu")
        wf.run()
    finally:
        root.mnist.loader.update(saved)
        root.mnist.decision.max_epochs = saved_epochs
    reg = telemetry.get_registry()
    assert reg.counter_total("veles_xla_cache_misses_total") >= 1
    text = reg.render_prometheus()
    assert "# TYPE veles_xla_build_seconds histogram" in text
    assert "veles_xla_dispatch_seconds_count" in text


# -- serving: JSON view + endpoints ------------------------------------

#: the exact pre-registry (PR 1/2 era) /metrics JSON key shape — the
#: satellite regression contract for /metrics.json consumers
GOLDEN_BATCHER_KEYS = {
    "queue_depth", "requests_total", "shed_total", "expired_total",
    "error_total", "batches_total", "batch_fill_ratio",
    "bucket_pad_ratio", "requests_per_sec",
    "latency_ms_p50", "latency_ms_p99",
}


def test_metrics_json_keeps_pre_registry_shape():
    from veles.serving.batcher import MicroBatcher
    b = MicroBatcher(lambda rows: (rows, len(rows)),
                     max_wait_ms=0.5, name="batcher-m", model="m")
    try:
        m0 = b.metrics()
        # before any completion the latency keys are absent — exactly
        # the pre-registry behaviour
        assert set(m0) == GOLDEN_BATCHER_KEYS - {
            "latency_ms_p50", "latency_ms_p99"}
        b.predict(numpy.zeros((2, 3), numpy.float32))
        m = b.metrics()
        assert set(m) == GOLDEN_BATCHER_KEYS
        assert m["requests_total"] == 1
        assert isinstance(m["requests_total"], int)
        assert m["batches_total"] == 1
        assert m["latency_ms_p50"] > 0
        json.dumps(m)               # JSON-serializable end to end
    finally:
        b.close()


class _StubRegistry:
    """Just enough ModelRegistry surface for the frontend."""

    def __init__(self, batcher):
        self._batcher = batcher

    def describe(self):
        return []

    def metrics(self):
        return {"m": self._batcher.metrics()}


def _get_raw(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode(), r.headers.get("Content-Type")


def test_frontend_metrics_endpoints():
    """/metrics is Prometheus text, /metrics.json the original JSON."""
    from veles.serving.batcher import MicroBatcher
    from veles.serving.frontend import ServingFrontend
    b = MicroBatcher(lambda rows: (rows, len(rows)),
                     max_wait_ms=0.5, name="batcher-m", model="m")
    front = None
    try:
        b.predict(numpy.zeros((1, 3), numpy.float32))
        front = ServingFrontend(_StubRegistry(b), port=0)
        base = "http://127.0.0.1:%d" % front.port
        doc = json.loads(_get_raw(base + "/metrics.json")[0])
        assert set(doc["models"]["m"]) == GOLDEN_BATCHER_KEYS
        text, ctype = _get_raw(base + "/metrics")
        assert ctype.startswith("text/plain")
        assert "# TYPE veles_serving_requests_total counter" in text
        assert 'veles_serving_requests_total{model="m"} 1' in text
        assert 'veles_serving_latency_seconds_count{model="m"} 1' \
            in text
    finally:
        if front is not None:
            front.close()
        b.close()


# -- web status: /metrics + escaping -----------------------------------


def test_web_status_metrics_and_html_escaping():
    from veles.web_status import WebStatus
    telemetry.counter("t_scrape_total").inc(3)
    ws = WebStatus(port=0)
    try:
        ws.register("evil", lambda: {
            "workflow": "<script>alert(1)</script>",
            "epoch": 1})
        base = "http://127.0.0.1:%d" % ws.port
        text, ctype = _get_raw(base + "/metrics")
        assert ctype.startswith("text/plain")
        assert "t_scrape_total 3" in text
        page = _get_raw(base + "/")[0]
        # provider values are untrusted page content: every cell is
        # escaped, a hostile workflow name cannot break the dashboard
        assert "<script>alert(1)</script>" not in page
        assert "&lt;script&gt;" in page
    finally:
        ws.close()


# -- cluster aggregation: one scrape sees the whole cluster ------------


def test_master_scrape_aggregates_slave_counters():
    from veles.client import SlaveClient
    from veles.server import MasterServer
    from tests.test_service import make_wf
    master_wf = make_wf("TeleMaster", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2)
    server.start_background()
    slave_wf = make_wf("TeleSlave")
    slave_wf.is_slave = True
    client = SlaveClient(slave_wf,
                         "127.0.0.1:%d" % server.bound_address[1],
                         name="tele-slave", io_timeout=10.0)
    jobs = client.run_forever()
    assert jobs > 0 and server.done.is_set()
    reg = telemetry.get_registry()
    # slave-pushed counters landed under slave="1" series
    assert reg.counter_total("veles_slave_jobs_done_total",
                             slave="1") >= 1
    assert reg.counter_total("veles_loader_samples_total",
                             slave="1", cls="train") > 0
    # master-side counters are in the same registry
    assert reg.counter_total("veles_cluster_faults_total",
                             kind="joins") >= 1
    assert reg.counter_total("veles_master_requests_total",
                             kind="update") >= jobs
    # the faults dict view matches the registry counters
    assert server.faults["joins"] == reg.counter_total(
        "veles_cluster_faults_total", kind="joins")
    text = reg.render_prometheus()
    assert 'slave="1"' in text
    assert "# TYPE veles_cluster_faults_total counter" in text


# -- logger satellite: JSONL postmortems -------------------------------


def test_jsonl_handler_serializes_exc_info(tmp_path):
    import logging
    from veles.logger import _JsonlHandler
    path = str(tmp_path / "log.jsonl")
    handler = _JsonlHandler(path)
    logger = logging.getLogger("tele-jsonl-test")
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)
    try:
        try:
            raise ValueError("boom for the postmortem")
        except ValueError:
            logger.exception("it failed")
        logger.info("plain line")
    finally:
        logger.removeHandler(handler)
    with open(path) as f:
        rows = [json.loads(line) for line in f]
    assert len(rows) == 2
    exc_row, plain_row = rows
    assert exc_row["msg"] == "it failed"
    assert "Traceback (most recent call last)" in exc_row["exc"]
    assert "boom for the postmortem" in exc_row["exc"]
    assert "ValueError" in exc_row["exc"]
    assert "exc" not in plain_row
    # timestamps are the records' own creation times, in order
    assert 0 < exc_row["t"] <= plain_row["t"]


def test_distributed_trace_merges_three_processes(tmp_path):
    """ISSUE 6 acceptance: a 2-slave training run with ``--trace-out``
    on the master produces ONE Perfetto file in which at least one
    job's dispatch, wire, slave-compute and merge spans share one
    trace_id across three real processes (master + 2 slaves), with
    per-process track names."""
    import socket
    import subprocess
    import threading
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    trace = str(tmp_path / "cluster.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    # enough jobs (10/epoch x 3) that BOTH slaves serve some even if
    # one's interpreter start lags the other by a few seconds — with
    # a handful of jobs the early slave drains the whole run alone
    # and the merged trace shows only two pids
    overrides = [
        os.path.join(REPO, "veles/znicz_tpu/models/mnist.py"),
        "root.mnist.decision.max_epochs=3",
        "root.mnist.loader.n_train=400",
        "root.mnist.loader.n_valid=100",
        "root.mnist.loader.minibatch_size=50",
        "-d", "numpy", "--no-stats", "--seed", "11",
    ]
    cli = [sys.executable, "-m", "veles"]
    master = subprocess.Popen(
        cli + overrides + ["--listen-address", "127.0.0.1:%d" % port,
                           "--trace-out", trace],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    slaves = [subprocess.Popen(
        cli + overrides + ["--master-address",
                           "127.0.0.1:%d" % port,
                           "--slave-retries", "60"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for _ in range(2)]

    def _drain(proc, sink):
        sink.append(proc.communicate()[0])

    outs = {p: [] for p in [master] + slaves}
    threads = [threading.Thread(target=_drain, args=(p, outs[p]))
               for p in [master] + slaves]
    for t in threads:
        t.start()
    try:
        master.wait(timeout=420)
        for p in slaves:
            p.wait(timeout=120)
    finally:
        for p in [master] + slaves:
            if p.poll() is None:
                p.kill()
    for t in threads:
        t.join(timeout=30)
    assert master.returncode == 0, outs[master]
    with open(trace) as f:
        doc = json.load(f)
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    pids = {e["pid"] for e in spans}
    assert len(pids) >= 3, "expected master+2 slave pids, got %s" % pids
    track_names = {e["args"]["name"] for e in meta
                   if e["name"] == "process_name"}
    assert "master" in track_names, track_names
    assert any(n.startswith("slave") for n in track_names), track_names
    by_trace = {}
    for e in spans:
        tid = e.get("args", {}).get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(e)
    want = {"job.dispatch", "job.wire", "slave.compute", "job.merge"}
    full = [evs for evs in by_trace.values()
            if want <= {e["name"] for e in evs}]
    assert full, "no job with the full causal chain: %s" % sorted(
        {e["name"] for evs in by_trace.values() for e in evs})
    # the chain genuinely crosses the process boundary
    chain = full[0]
    master_pid = next(e["pid"] for e in chain
                      if e["name"] == "job.dispatch")
    slave_pid = next(e["pid"] for e in chain
                     if e["name"] == "slave.compute")
    assert master_pid != slave_pid


# -- CLI acceptance: --trace-out on a sample run -----------------------


def test_velescli_trace_out(tmp_path):
    trace = str(tmp_path / "trace.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "velescli.py"),
         os.path.join(REPO, "veles/znicz_tpu/models/mnist.py"),
         "root.mnist.loader.n_train=120",
         "root.mnist.loader.n_valid=40",
         "root.mnist.loader.minibatch_size=40",
         "root.mnist.decision.max_epochs=1",
         "-d", "numpy", "--seed", "7", "--no-stats",
         "--trace-out", trace],
        env=env, cwd=REPO, capture_output=True, text=True,
        timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "trace -> %s" % trace in r.stdout
    with open(trace) as f:
        doc = json.load(f)
    # span events plus ph="M" process_name metadata (the launcher
    # names this pid's track — ISSUE 6 per-process track names)
    events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert events, "empty trace"
    assert any(e["name"] == "process_name" for e in meta)
    for e in events:
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["dur"], (int, float))
    names = {e["name"] for e in events}
    assert "workflow.run" in names
    assert any(n.endswith(".run") for n in names - {"workflow.run"})
