"""Observability tail (SURVEY.md §2.7 rows 4-5, §5.5): plot rendering,
the graphics server -> renderer-process stream, the web-status
dashboard, and ImageSaver."""

import json
import os
import time
import urllib.request

import numpy
import pytest

import veles.prng as prng
from veles.config import root


# -- renderers (pure functions) ---------------------------------------


def test_render_kinds(tmp_path):
    from veles.graphics_client import render_payload
    rng = numpy.random.default_rng(3)
    cases = [
        ({"kind": "curves", "name": "curves", "title": "t"},
         {"train": rng.random(5).astype(numpy.float32),
          "validation": rng.random(5).astype(numpy.float32)}),
        ({"kind": "image", "name": "img"},
         {"image": rng.random((8, 8)).astype(numpy.float32)}),
        ({"kind": "grid", "name": "grid"},
         {"tiles": rng.random((10, 5, 5)).astype(numpy.float32)}),
        ({"kind": "matrix", "name": "mat"},
         {"matrix": (rng.random((4, 4)) * 9).astype(numpy.int32)}),
    ]
    for meta, arrays in cases:
        path = render_payload(meta, arrays, str(tmp_path))
        assert os.path.exists(path) and os.path.getsize(path) > 500


def test_payload_roundtrip():
    from veles.graphics import pack_payload, unpack_payload
    meta = {"kind": "image", "name": "x", "cmap": "hot"}
    arrays = {"image": numpy.arange(12, dtype=numpy.float32)
              .reshape(3, 4)}
    m2, a2 = unpack_payload(pack_payload(meta, arrays))
    assert m2 == meta
    numpy.testing.assert_array_equal(a2["image"], arrays["image"])


# -- graphics server + renderer subprocess ----------------------------


def test_graphics_stream_end_to_end(tmp_path):
    from veles.graphics import GraphicsServer
    out = str(tmp_path / "plots")
    srv = GraphicsServer(out)
    try:
        # wait for the subprocess to connect
        deadline = time.time() + 20
        sent = False
        payload = ({"kind": "image", "name": "som", "title": "hits"},
                   {"image": numpy.eye(6, dtype=numpy.float32)})
        while time.time() < deadline:
            if srv.publish(*payload):
                sent = True
                break
            time.sleep(0.05)
        assert sent, "renderer never connected"
    finally:
        srv.close()
    png = os.path.join(out, "som.png")
    assert os.path.exists(png) and os.path.getsize(png) > 500
    with open(os.path.join(out, "plots.json")) as f:
        assert json.load(f)["som"]["kind"] == "image"


# -- plot units on a real workflow ------------------------------------


def _mnist_wf(name, backend="numpy", **decision):
    prng.seed_all(404)
    from veles.znicz_tpu.models import mnist
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    root.mnist.loader.update(
        {"n_train": 200, "n_valid": 80, "minibatch_size": 40})
    root.mnist.decision.max_epochs = decision.get("max_epochs", 2)
    try:
        wf = mnist.create_workflow(name=name)
        yield wf
    finally:
        root.mnist.loader.update(saved)
        root.mnist.decision.max_epochs = 5


def test_plotters_render_in_process(tmp_path):
    gen = _mnist_wf("PlotWF")
    wf = next(gen)
    out = str(tmp_path / "plots")
    wf.link_plotters(out_dir=out)
    wf.initialize(device="numpy")
    wf.run()
    try:
        next(gen)
    except StopIteration:
        pass
    assert os.path.exists(os.path.join(out, "plot_metric.png"))
    assert os.path.exists(os.path.join(out, "plot_weights.png"))


def test_plotters_fused_path(tmp_path):
    gen = _mnist_wf("PlotWFX")
    wf = next(gen)
    out = str(tmp_path / "plotsx")
    wf.link_plotters(out_dir=out)
    wf.initialize(device="cpu")
    wf.run()
    try:
        next(gen)
    except StopIteration:
        pass
    assert os.path.exists(os.path.join(out, "plot_metric.png"))


# -- image saver ------------------------------------------------------


@pytest.mark.parametrize("backend", ["numpy", "cpu"])
def test_image_saver(tmp_path, backend):
    gen = _mnist_wf("Saver_%s" % backend)
    wf = next(gen)
    out = str(tmp_path / "misses")
    wf.link_image_saver(out, limit_per_epoch=8)
    wf.initialize(device=backend)
    wf.run()
    try:
        next(gen)
    except StopIteration:
        pass
    saved = []
    for d, _, files in os.walk(out):
        saved += [os.path.join(d, f) for f in files]
    assert saved, "no samples dumped"
    arr = numpy.load(saved[0])
    assert arr.shape == (784,)
    assert wf.image_saver.total_saved == len(saved)


def test_weights2d_conv_layer(tmp_path):
    """Weights2D on a CONV first layer (weights are (n_kernels,
    fan_in) — regression: the dense-layer transpose must not apply)."""
    prng.seed_all(505)
    from veles.znicz_tpu.models import cifar10
    saved = {k: root.cifar.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    root.cifar.loader.update(
        {"n_train": 100, "n_valid": 50, "minibatch_size": 50})
    root.cifar.decision.max_epochs = 1
    out = str(tmp_path / "convplots")
    try:
        wf = cifar10.create_workflow(name="ConvPlot")
        wf.link_plotters(out_dir=out)
        wf.initialize(device="numpy")
        wf.run()
    finally:
        root.cifar.loader.update(saved)
    png = os.path.join(out, "plot_weights.png")
    assert os.path.exists(png) and os.path.getsize(png) > 500


def test_kohonen_hits_plotter(tmp_path):
    prng.seed_all(11)
    from veles.znicz_tpu.models import kohonen
    from veles.znicz_tpu.nn_plotting_units import (
        KohonenHits, KohonenNeighborMap)
    root.kohonen.decision.max_epochs = 2
    root.kohonen.loader.n_samples = 200
    wf = kohonen.create_workflow(name="SomPlot")
    out = str(tmp_path / "som")
    for cls, name in ((KohonenHits, "som_hits"),
                      (KohonenNeighborMap, "som_umatrix")):
        u = cls(wf, forward=wf.forwards[0], name=name, out_dir=out)
        u.link_from(wf.decision)
        u.gate_skip = ~wf.decision.epoch_ended
    wf.initialize(device="numpy")
    wf.run()
    for name in ("som_hits", "som_umatrix"):
        png = os.path.join(out, name + ".png")
        assert os.path.exists(png) and os.path.getsize(png) > 500


# -- web status -------------------------------------------------------


def test_web_status(tmp_path):
    from veles.web_status import WebStatus, workflow_status
    gen = _mnist_wf("WebWF")
    wf = next(gen)
    wf.initialize(device="numpy")
    wf.run()
    try:
        next(gen)
    except StopIteration:
        pass
    ws = WebStatus(port=0)
    try:
        ws.register(wf.name, workflow_status(wf))
        base = "http://127.0.0.1:%d" % ws.port
        doc = json.loads(urllib.request.urlopen(
            base + "/status.json", timeout=10).read())
        assert doc["WebWF"]["epoch"] == 2
        assert doc["WebWF"]["complete"] is True
        page = urllib.request.urlopen(base + "/", timeout=10) \
            .read().decode()
        assert "WebWF" in page
        # remote launcher POST
        req = urllib.request.Request(
            base + "/update",
            data=json.dumps({"name": "slave0", "mode": "slave",
                             "epoch": 7}).encode(),
            method="POST")
        urllib.request.urlopen(req, timeout=10)
        doc = json.loads(urllib.request.urlopen(
            base + "/status.json", timeout=10).read())
        assert doc["slave0"]["epoch"] == 7
    finally:
        ws.close()


def test_web_status_update_name_cap_413(monkeypatch):
    """Admission hardening (zlint unbounded-cardinality): POST
    /update names are the poster's choice and each novel one is a
    dict kept forever — past the cap, novel names get 413 while
    updates to existing names still land."""
    import veles.web_status as web_status
    from veles.web_status import WebStatus
    monkeypatch.setattr(web_status, "_MAX_PUSHED", 2)
    ws = WebStatus(port=0)
    try:
        base = "http://127.0.0.1:%d" % ws.port

        def post(name):
            req = urllib.request.Request(
                base + "/update",
                data=json.dumps({"name": name,
                                 "epoch": 1}).encode(),
                method="POST")
            return urllib.request.urlopen(req, timeout=10).status

        assert post("a") == 200
        assert post("b") == 200
        with pytest.raises(urllib.error.HTTPError) as err:
            post("c")
        assert err.value.code == 413
        # existing names keep updating under the cap
        assert post("a") == 200
        doc = json.loads(urllib.request.urlopen(
            base + "/status.json", timeout=10).read())
        assert sorted(doc) == ["a", "b"]
    finally:
        ws.close()


def test_profile_dir_writes_trace(tmp_path):
    """--profile-dir wraps the run in jax.profiler.trace and leaves a
    trace artifact behind (SURVEY §5.1 kernel-level profiling)."""
    import os
    import veles.prng as prng
    from veles.config import root
    from veles.launcher import Launcher
    prng.seed_all(5)
    from veles.znicz_tpu.models import mnist
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    saved_epochs = root.mnist.decision.get("max_epochs")
    root.mnist.loader.update({"n_train": 64, "n_valid": 32,
                              "minibatch_size": 16})
    root.mnist.decision.max_epochs = 1
    prof = str(tmp_path / "trace")
    try:
        wf = mnist.create_workflow(name="ProfiledRun")
        launcher = Launcher(device="xla", stats=False,
                            profile_dir=prof)
        launcher.initialize(wf)
        launcher.run()
    finally:
        root.mnist.loader.update(saved)
        root.mnist.decision.max_epochs = saved_epochs
    found = [os.path.join(dp, f) for dp, _, fs in os.walk(prof)
             for f in fs]
    assert found, "no profiler trace files written"
