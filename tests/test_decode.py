"""Generative serving (ISSUE 11): paged KV cache, continuous
batching, streaming decode over the reactor.

The exactness anchor: the spec-walking serving decode must produce
token-for-token what the unit-walking offline ``generate()`` produces
— both paths ride the same shared math
(``dense_attention_core_fwd``/``block_fwd``/``attn_decode``), so a
drift here means the decode plane re-invented a formula.

HTTP coverage (satellite): a chunked ``/v1/generate`` response read
token by token over a REAL socket with the first chunk arriving while
the decode batch is still in flight, a client disconnect mid-stream
freeing its KV slot and counting
``veles_serving_rejected_total{reason="disconnect"}``, and probe
endpoints answering fast while decoding.
"""

import json
import os
import socket
import time
import urllib.error
import urllib.request

import numpy
import pytest

import veles.prng as prng
from veles.config import root

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- shared artifact ---------------------------------------------------


def _export_lm(base, name, stacked=False):
    """Initialize (untrained — decode prices machinery, not model
    quality) + export a tiny LM; returns (workflow, archive_dir)."""
    prng.seed_all(4242)
    from veles.znicz_tpu.models import transformer_lm
    saved_loader = root.lm.loader.to_dict()
    saved_model = root.lm.model.to_dict()
    root.lm.loader.update({"minibatch_size": 8, "n_train": 64,
                           "n_valid": 16, "seq_len": 16, "vocab": 8,
                           "max_period": 4})
    root.lm.model.update({"dim": 16, "heads": 2, "layers": 2,
                          "ffn_hidden": 32, "moe_experts": 0,
                          "attn_block": None, "attn_impl": None,
                          "stacked": stacked})
    try:
        wf = transformer_lm.create_workflow(name=name)
        wf.initialize(device="numpy")
        archive = str(base / ("archive_stacked" if stacked
                              else "archive"))
        wf.export_inference(archive)
        return wf, archive
    finally:
        root.lm.loader.update(saved_loader)
        root.lm.model.update(saved_model)


@pytest.fixture(scope="module")
def lm_env(tmp_path_factory):
    """One tiny LM archive + its live workflow (the offline-generate
    oracle) + a shared registry whose decode plane every HTTP test
    reuses (compiled programs are the expensive part)."""
    from veles.serving import ModelRegistry
    base = tmp_path_factory.mktemp("decode")
    wf, archive = _export_lm(base, "DecodeLM")
    registry = ModelRegistry(backend="numpy", decode_slots=4,
                             decode_max_len=256, decode_max_queue=2)
    registry.load("lm", archive)
    yield {"wf": wf, "archive": archive, "registry": registry,
           "base": base}
    registry.close()


def _offline(wf, prompt, n):
    from veles.znicz_tpu.generate import generate
    return generate(wf, numpy.asarray([prompt], numpy.int32), n,
                    temperature=0.0)[0].tolist()


# -- plan + engine -----------------------------------------------------


def test_plan_probe_and_rejection(lm_env, tmp_path):
    """Only causal-LM archives build a decode plan; a classifier
    archive is rejected loudly (and probe() says so quietly)."""
    from veles.serving import ArchiveModel, DecodePlan
    model = lm_env["registry"].get("lm").model
    assert DecodePlan.probe(model)
    plan = DecodePlan.from_archive(model)
    assert plan.n_caches == 2 and plan.vocab == 8
    # hand-written non-generative archive: a lone dense layer
    numpy.save(tmp_path / "fc_weights.npy",
               numpy.zeros((4, 4), numpy.float32))
    (tmp_path / "contents.json").write_text(json.dumps({
        "format": 1, "workflow": "clf", "input_sample_shape": [4],
        "units": [{"type": "all2all", "name": "fc",
                   "config": {"neurons": 4,
                              "output_sample_shape": [4]},
                   "weights": "fc_weights.npy", "bias": None}]}))
    clf = ArchiveModel.from_dir(str(tmp_path))
    assert not DecodePlan.probe(clf)
    with pytest.raises(ValueError, match="embedding"):
        DecodePlan.from_archive(clf)


def test_decode_matches_offline_generate(lm_env):
    """Greedy continuous decode == the unit-walking generate(),
    token for token — including two concurrent sequences of
    DIFFERENT lengths sharing the decode batch; sampled decode stays
    inside the vocabulary."""
    registry, wf = lm_env["registry"], lm_env["wf"]
    decoder = registry.decoder("lm")
    assert decoder is registry.decoder("lm")      # built once
    toks = decoder.generate([1, 2, 3, 1, 2, 3], max_tokens=8)
    assert toks == _offline(wf, [1, 2, 3, 1, 2, 3], 8)
    h1 = decoder.submit([1, 2, 3, 4, 5], max_tokens=12)
    h2 = decoder.submit([5, 6, 5], max_tokens=6)
    assert h1.wait(120) == _offline(wf, [1, 2, 3, 4, 5], 12)
    assert h2.wait(120) == _offline(wf, [5, 6, 5], 6)
    assert h1.finish_reason == h2.finish_reason == "length"
    assert decoder.engine.pool.in_use == 0        # slots recycled
    sampled = decoder.generate([1, 2, 3], max_tokens=8,
                               temperature=1.0)
    assert len(sampled) == 8
    assert all(0 <= t < 8 for t in sampled)


def test_decode_stacked_archive(lm_env):
    """The fused transformer_stack archive decodes through
    block_fwd/block_decode and matches the offline oracle too."""
    from veles.serving import (ArchiveModel, ContinuousBatcher,
                               GenerativeEngine)
    wf, archive = _export_lm(lm_env["base"], "DecodeStackLM",
                             stacked=True)
    engine = GenerativeEngine(ArchiveModel.from_dir(archive),
                              n_slots=2, max_len=32)
    batcher = ContinuousBatcher(engine, model="stack")
    try:
        toks = batcher.generate([1, 2, 1, 2, 1], max_tokens=6)
        assert toks == _offline(wf, [1, 2, 1, 2, 1], 6)
    finally:
        batcher.close()


def test_midflight_admission_eos_and_sharing(lm_env):
    """A request submitted while another decodes joins the IN-FLIGHT
    batch (shared steps, not appended ones), and an EOS hit frees its
    slot mid-flight without disturbing its neighbour."""
    registry, wf = lm_env["registry"], lm_env["wf"]
    decoder = registry.decoder("lm")
    steps0 = int(decoder._c_steps.get().value)
    long = decoder.submit([1, 2, 3, 4], max_tokens=60)
    # wait until the long request is genuinely decoding
    deadline = time.time() + 30
    while time.time() < deadline and len(long.tokens) < 3:
        time.sleep(0.005)
    assert len(long.tokens) >= 3
    want_short = _offline(wf, [5, 6, 5, 6], 30)
    # eos = the short request's own 3rd token -> it must stop there
    eos = want_short[2]
    short = decoder.submit([5, 6, 5, 6], max_tokens=30, eos=eos)
    got_short = short.wait(120)
    assert short.finish_reason == "eos"
    assert got_short == want_short[:got_short.index(eos) + 1]
    assert got_short[-1] == eos and len(got_short) <= 3
    got_long = long.wait(120)
    assert got_long == _offline(wf, [1, 2, 3, 4], 60)
    # sharing: the joined window advanced BOTH sequences per step, so
    # total steps stayed well under the sum of solo decodes
    steps = int(decoder._c_steps.get().value) - steps0
    assert steps < 60 + len(got_short)
    assert decoder.engine.pool.in_use == 0


def test_decode_shedding_and_validation(lm_env):
    """Admission is bounded: with every KV slot busy and the queue at
    max_queue, the next submit sheds (QueueFull -> the frontend's
    503); geometry violations are client errors before any slot is
    touched."""
    from veles.serving import QueueFull
    registry = lm_env["registry"]
    decoder = registry.decoder("lm")
    with pytest.raises(ValueError, match="max_len|KV slot"):
        decoder.submit([1] * 8, max_tokens=1000)
    with pytest.raises(ValueError):
        decoder.submit([], max_tokens=4)
    # non-finite client numbers are admission errors, not wedged
    # deadlines (NaN never compares expired) or OverflowError 500s
    with pytest.raises(ValueError, match="timeout_ms"):
        decoder.submit([1], timeout_ms=float("nan"))
    with pytest.raises(ValueError, match="timeout_ms"):
        decoder.submit([1], timeout_ms=float("inf"))
    with pytest.raises(ValueError, match="timeout_ms"):
        decoder.submit([1], timeout_ms=-5)
    with pytest.raises(ValueError, match="max_tokens"):
        decoder.submit([1], max_tokens=float("inf"))
    held = []
    try:
        for _ in range(4):                        # fill the 4 slots,
            h = decoder.submit([1, 2, 3], max_tokens=250)
            held.append(h)                        # waiting for each
            deadline = time.time() + 30           # admission so the
            while time.time() < deadline and not h.tokens:
                time.sleep(0.005)                 # queue stays empty
            assert h.tokens
        with pytest.raises(QueueFull):
            for _ in range(4):                    # queue cap is 2
                held.append(decoder.submit([1, 2], max_tokens=250))
        assert int(decoder._c_shed.get().value) >= 1
    finally:
        for h in held:
            h.cancel("test cleanup")
        for h in held:
            h.wait(120)
    deadline = time.time() + 10
    while time.time() < deadline and decoder.engine.pool.in_use:
        time.sleep(0.01)
    assert decoder.engine.pool.in_use == 0


def test_queued_request_expires_while_pool_saturated(lm_env):
    """Review regression: a queued request whose deadline passes
    while every KV slot is busy must expire (504) at the next step
    boundary — dead entries must not pin the bounded queue while
    long generations hold the pool."""
    from veles.serving import DeadlineExceeded
    decoder = lm_env["registry"].decoder("lm")
    held = []
    try:
        for _ in range(4):                        # saturate the pool
            h = decoder.submit([1, 2, 3], max_tokens=250)
            held.append(h)
            deadline = time.time() + 30
            while time.time() < deadline and not h.tokens:
                time.sleep(0.005)
        doomed = decoder.submit([1, 2], max_tokens=5, timeout_ms=40)
        with pytest.raises(DeadlineExceeded):
            doomed.wait(15)
        # it expired while the pool was STILL saturated
        assert decoder.engine.pool.in_use == 4
        assert int(decoder._c_expired.get().value) >= 1
    finally:
        for h in held:
            h.cancel("test cleanup")
        for h in held:
            h.wait(120)


def test_reload_and_unload_close_decode_plane(lm_env):
    """Review regression: an architecture-changing hot reload (and an
    unload) must close the OLD decode plane — worker stopped, KV pool
    released — instead of leaking it alongside the replacement."""
    from veles.serving import ModelRegistry
    _, stacked = _export_lm(lm_env["base"], "ReloadStackLM",
                            stacked=True)
    reg = ModelRegistry(backend="numpy", decode_slots=2,
                        decode_max_len=32)
    try:
        reg.load("m", lm_env["archive"])
        dec = reg.decoder("m")
        assert dec._running
        reg.load("m", stacked)          # different signature()
        assert not dec._running         # old plane closed
        with pytest.raises(RuntimeError, match="closed"):
            dec.submit([1], max_tokens=1)
        dec2 = reg.decoder("m")
        assert dec2 is not dec and dec2._running
        reg.unload("m")
        assert not dec2._running
        with pytest.raises(KeyError):
            reg.decoder("m")
    finally:
        reg.close()


def test_kv_pool_accounting(lm_env):
    """ISSUE 11 memory accounting: building the decode plane grows
    the entry's forward-cache estimate by exactly the preallocated
    KV pool bytes, and the pool gauges land on /metrics."""
    from veles import telemetry
    from veles.serving import ModelRegistry
    reg = ModelRegistry(backend="numpy", decode_slots=2,
                        decode_max_len=32)
    try:
        entry = reg.load("m", lm_env["archive"])
        assert entry.describe()["generative"] is True
        before = entry.cache_bytes()
        decoder = reg.decoder("m")
        pool = decoder.engine.pool
        # 2 layers x (K+V) x slots x heads x max_len x dh x 4B
        assert pool.nbytes() == 2 * 2 * 2 * 2 * 32 * 8 * 4
        assert entry.cache_bytes() == before + pool.nbytes()
        assert entry.describe()["decode"]["kv_pool_slots"] == 2
        text = telemetry.get_registry().render_prometheus()
        assert 'veles_serving_kv_pool_slots{model="m"} 2' in text
        assert reg.metrics()["m"]["decode"]["kv_pool_slots"] == 2
    finally:
        reg.close()


# -- HTTP: streaming over the reactor ----------------------------------


@pytest.fixture
def front(lm_env):
    from veles.serving.frontend import ServingFrontend
    f = ServingFrontend(lm_env["registry"], port=0)
    yield f
    f.close()


def _stream_generate(port, doc, stop_after=None, timeout=60):
    """POST /v1/generate over a raw socket; -> (headers, list of
    (arrival_time, parsed_line)). ``stop_after=N`` closes the socket
    after N token lines (the disconnecting client)."""
    s = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    body = json.dumps(doc).encode()
    s.sendall(b"POST /v1/generate HTTP/1.1\r\nHost: t\r\n"
              b"Content-Type: application/json\r\n"
              b"Content-Length: %d\r\n\r\n%s" % (len(body), body))
    buf = b""
    while b"\r\n\r\n" not in buf:
        buf += s.recv(4096)
    head, _, buf = buf.partition(b"\r\n\r\n")
    lines = []          # (arrival wall time, parsed json line)
    chunks = b""
    done = False
    while not done:
        # parse complete chunks out of buf
        progressed = True
        while progressed:
            progressed = False
            if b"\r\n" in buf:
                size_s, _, rest = buf.partition(b"\r\n")
                try:
                    n = int(size_s, 16)
                except ValueError:
                    raise AssertionError("bad chunk size %r" % size_s)
                if n == 0:
                    done = True
                    break
                if len(rest) >= n + 2:
                    chunks += rest[:n]
                    buf = rest[n + 2:]
                    progressed = True
        now = time.perf_counter()
        while b"\n" in chunks:
            line, _, chunks = chunks.partition(b"\n")
            lines.append((now, json.loads(line)))
        n_tokens = sum(1 for _, d in lines if "token" in d)
        if stop_after is not None and n_tokens >= stop_after:
            s.close()
            return head.decode("latin-1"), lines
        if done:
            break
        data = s.recv(4096)
        if not data:
            break
        buf += data
    s.close()
    return head.decode("latin-1"), lines


def test_http_generate_streams_incrementally(lm_env, front):
    """THE acceptance path: >=16 tokens arrive as separate chunked
    reads over a real socket, and the FIRST token is read while the
    decode batch is still in flight (the server-side slot is still
    occupied when the client holds token #1)."""
    registry, wf = lm_env["registry"], lm_env["wf"]
    decoder = registry.decoder("lm")
    port = front.port
    s = socket.create_connection(("127.0.0.1", port), timeout=60)
    body = json.dumps({"model": "lm", "prompt": [1, 2, 3],
                       "max_tokens": 200}).encode()
    s.sendall(b"POST /v1/generate HTTP/1.1\r\nHost: t\r\n"
              b"Content-Type: application/json\r\n"
              b"Content-Length: %d\r\n\r\n%s" % (len(body), body))
    buf = b""
    while b'"token"' not in buf:
        buf += s.recv(4096)
    # first token is in hand — the sequence must still be decoding
    mid_flight = decoder.engine.pool.in_use
    t_first = time.perf_counter()
    reads = 1
    while b"0\r\n\r\n" not in buf:
        data = s.recv(4096)
        if not data:
            break
        reads += 1
        buf += data
    t_last = time.perf_counter()
    s.close()
    head, _, rest = buf.partition(b"\r\n\r\n")
    assert b"Transfer-Encoding: chunked" in head
    # re-assemble the chunked body and check the token ledger
    payload = b""
    while rest:
        size_s, _, rest = rest.partition(b"\r\n")
        n = int(size_s, 16)
        if n == 0:
            break
        payload += rest[:n]
        rest = rest[n + 2:]
    docs = [json.loads(l)
            for l in payload.decode().strip().split("\n")]
    toks = [d["token"] for d in docs if "token" in d]
    final = docs[-1]
    assert final["done"] and final["tokens"] == toks
    assert len(toks) == 200 >= 16
    assert toks == _offline(wf, [1, 2, 3], 200)
    # incrementality, two independent witnesses: the slot was still
    # occupied when token #1 was read, and the tail arrived across
    # many separate socket reads spread over real time
    assert mid_flight >= 1 or t_last - t_first > 0.01
    assert reads > 4


def test_http_generate_disconnect_frees_slot(lm_env, front):
    """Satellite: a client dropping mid-stream frees its KV slot at
    the next step boundary and counts a
    veles_serving_rejected_total{reason="disconnect"}."""
    from veles import telemetry
    registry = lm_env["registry"]
    decoder = registry.decoder("lm")
    head, lines = _stream_generate(
        front.port, {"model": "lm", "prompt": [1, 2],
                     "max_tokens": 250}, stop_after=2)
    assert "200" in head.split("\r\n")[0]
    deadline = time.time() + 15
    while time.time() < deadline and decoder.engine.pool.in_use:
        time.sleep(0.02)
    assert decoder.engine.pool.in_use == 0
    assert telemetry.get_registry().counter_total(
        "veles_serving_rejected_total", reason="disconnect") >= 1
    # the abandoned generation was cancelled, not run to completion
    assert decoder._c_finished.get().labels(
        "lm", "disconnect").value >= 1


def test_http_generate_nonstream_and_errors(lm_env, front, tmp_path):
    """stream:false answers once with the same greedy tokens; error
    paths: 404 unknown model, 400 non-generative archive, 400 bad
    geometry, 400 bad json."""
    wf = lm_env["wf"]
    base = "http://127.0.0.1:%d" % front.port

    def post(doc, raw=None):
        req = urllib.request.Request(
            base + "/v1/generate",
            raw if raw is not None else json.dumps(doc).encode(),
            method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, json.loads(resp.read())

    code, doc = post({"model": "lm", "prompt": [1, 2, 3],
                      "max_tokens": 12, "stream": False})
    assert code == 200
    assert doc["tokens"] == _offline(wf, [1, 2, 3], 12)
    assert doc["finish_reason"] == "length" and doc["n"] == 12
    with pytest.raises(urllib.error.HTTPError) as err:
        post({"model": "nope", "prompt": [1], "stream": False})
    assert err.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as err:
        post({"model": "lm", "prompt": [1], "max_tokens": 5000,
              "stream": False})
    assert err.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as err:
        post(None, raw=b"{not json")
    assert err.value.code == 400
    # JSON carries bare NaN/Infinity and Python's parser accepts
    # them: a NaN timeout would mint a deadline that never expires
    # and an Infinity budget would OverflowError into a 500 — both
    # must be refused as client errors (admission hardening)
    with pytest.raises(urllib.error.HTTPError) as err:
        post(None, raw=b'{"model": "lm", "prompt": [1], '
             b'"timeout_ms": NaN, "stream": false}')
    assert err.value.code == 400
    assert "timeout_ms" in json.loads(err.value.read())["error"]
    with pytest.raises(urllib.error.HTTPError) as err:
        post(None, raw=b'{"model": "lm", "prompt": [1], '
             b'"max_tokens": Infinity, "stream": false}')
    assert err.value.code == 400
    assert "max_tokens" in json.loads(err.value.read())["error"]
    # a loaded NON-generative model answers 400, not 500
    numpy.save(tmp_path / "fc_weights.npy",
               numpy.zeros((4, 4), numpy.float32))
    (tmp_path / "contents.json").write_text(json.dumps({
        "format": 1, "workflow": "clf", "input_sample_shape": [4],
        "units": [{"type": "all2all", "name": "fc",
                   "config": {"neurons": 4,
                              "output_sample_shape": [4]},
                   "weights": "fc_weights.npy", "bias": None}]}))
    lm_env["registry"].load("clf", str(tmp_path))
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            post({"model": "clf", "prompt": [1], "stream": False})
        assert err.value.code == 400
        assert "embedding" in json.loads(err.value.read())["error"]
    finally:
        lm_env["registry"].unload("clf")


def test_probes_fast_while_decode_in_flight(lm_env, front):
    """Satellite: /healthz and /readyz answer inline on the loop in
    well under 0.5s while a decode batch runs — and the readiness doc
    carries the serving:<port>:decode check."""
    registry = lm_env["registry"]
    decoder = registry.decoder("lm")
    handle = decoder.submit([1, 2, 3], max_tokens=250)
    base = "http://127.0.0.1:%d" % front.port
    try:
        worst = 0.0
        for _ in range(5):
            for path in ("/healthz", "/readyz"):
                t0 = time.perf_counter()
                with urllib.request.urlopen(base + path,
                                            timeout=10) as resp:
                    doc = json.loads(resp.read())
                worst = max(worst, time.perf_counter() - t0)
                if path == "/readyz":
                    assert "serving:%d:decode" % front.port \
                        in doc["checks"]
        assert worst < 0.5, worst
    finally:
        handle.cancel("test done")
        handle.wait(120)


def test_decode_readiness_flips_on_dead_worker(lm_env):
    """serving:<port>:decode goes not-ready when a model's decode
    worker dies (and names the model)."""
    from veles.serving.frontend import ServingFrontend
    f = ServingFrontend(lm_env["registry"], port=0)
    try:
        decoder = lm_env["registry"].decoder("lm")
        ok, why = f._check_decode()
        assert ok, why
        # simulate a crashed (not closed) worker
        was_running = decoder._running
        try:
            alive = decoder._thread
            decoder._thread = _DeadThread()
            ok, why = f._check_decode()
            assert not ok and "lm" in why
        finally:
            decoder._thread = alive
            decoder._running = was_running
        ok, _ = f._check_decode()
        assert ok
    finally:
        f.close()


class _DeadThread:
    @staticmethod
    def is_alive():
        return False


# -- bench acceptance (slow soak) --------------------------------------


@pytest.mark.slow
def test_bench_continuous_beats_sequential_2x():
    """ISSUE 11 acceptance: >=2x aggregate tokens/s for continuous
    batching over sequential per-request decode at 8 concurrent
    streams (the bench row's own code path; measured ~7x on the CI
    container)."""
    import bench
    seq, cont, first = bench.generate_decode_tokens_per_sec()
    assert cont >= 2.0 * seq, (seq, cont)
    assert first is not None and first < 5.0


def test_bench_generate_rows_shape(monkeypatch):
    """The bench wrapper records the three keys (or one error key)
    and the directionality table knows first-token latency is a
    cost."""
    import bench
    assert any(s in "generate_first_token_latency_s"
               for s in bench._LOWER_BETTER)
    monkeypatch.setattr(
        bench, "generate_decode_tokens_per_sec",
        lambda **kw: (100.0, 400.0, 0.02))
    extra = {}
    bench._generate_rows(extra)
    assert extra == {
        "generate_tokens_per_sec_sequential": 100.0,
        "generate_tokens_per_sec_continuous": 400.0,
        "generate_first_token_latency_s": 0.02}
