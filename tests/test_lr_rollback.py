"""LR scheduling policies + NNRollback (SURVEY.md §2.4 "LR scheduling"
/ "Divergence rollback").

The schedule must be applied INSIDE the compiled step (the iteration
counter is traced STATE), agree between the numpy oracle and the XLA
path, and survive multi-epoch fused dispatches without retraces."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root
from veles.znicz_tpu.lr_adjust import (
    StepPolicy, ExpPolicy, InvPolicy, ArbitraryStepPolicy, make_policy)


@pytest.mark.parametrize("policy", [
    StepPolicy(gamma=0.5, step=3),
    ExpPolicy(gamma=0.9),
    InvPolicy(gamma=0.01, power=0.5),
    ArbitraryStepPolicy([(0.1, 2), (0.01, 3), (0.001, 1)]),
])
def test_policy_traced_matches_numpy(policy):
    """Each policy formula gives identical values under numpy and under
    jax.jit tracing (same function, both backends)."""
    import jax
    import jax.numpy as jnp

    base = numpy.float32(0.04)
    fn = jax.jit(lambda t: policy(jnp, base, t))
    for t in range(8):
        expect = policy(numpy, base, t)
        got = float(fn(jnp.int32(t)))
        assert abs(float(expect) - got) < 1e-7, (t, expect, got)


def test_make_policy_from_dict():
    p = make_policy({"name": "step", "gamma": 0.25, "step": 10})
    assert isinstance(p, StepPolicy)
    assert p.gamma == 0.25 and p.step == 10
    assert make_policy(None) is None
    assert make_policy(p) is p


def _mnist_wf(backend, name, policy=None, max_epochs=3, lr=0.02):
    prng.seed_all(4242)
    from veles.znicz_tpu.models import mnist
    saved = {k: getattr(root.mnist.loader, k, None)
             for k in ("minibatch_size", "n_train", "n_valid")}
    root.mnist.loader.update({"minibatch_size": 25,
                              "n_train": 200, "n_valid": 50})
    root.mnist.decision.max_epochs = max_epochs
    try:
        wf = mnist.create_workflow(name=name)
        for gd in wf.gds:
            gd.learning_rate = lr
            gd.learning_rate_bias = lr
        if policy is not None:
            wf.link_lr_adjuster(policy)
        wf.initialize(device=backend)
        wf.run()
    finally:
        root.mnist.loader.update(
            {k: v for k, v in saved.items() if v is not None})
    return wf


def test_schedule_parity_numpy_vs_xla():
    """MNIST trained under a step policy: oracle and compiled paths
    follow the same schedule (weights stay close, history matches)."""
    policy = {"name": "step", "gamma": 0.5, "step": 10}
    wf_np = _mnist_wf("numpy", "LrNp", policy)
    wf_x = _mnist_wf("cpu", "LrXla", policy)
    for a, b in zip(wf_np.decision.history, wf_x.decision.history):
        assert abs(a["train"]["loss"] - b["train"]["loss"]) < 5e-3, \
            (a, b)
    w_np = wf_np.forwards[0].weights.map_read().mem
    w_x = wf_x.forwards[0].weights.map_read().mem
    assert numpy.allclose(w_np, w_x, atol=5e-3)
    # counter advanced once per train minibatch; the numpy graph skips
    # the GD chain on the final minibatch once decision.complete fires
    # (gate_skip), the fused epoch applies it — long-standing 1-step
    # tail difference between the paths
    n_train_steps = 3 * (200 // 25)
    assert int(wf_x.gds[0].iteration.map_read().mem) == n_train_steps
    assert int(wf_np.gds[0].iteration.map_read().mem) == n_train_steps - 1


def test_zero_lr_schedule_freezes_weights_inside_compiled_step():
    """An all-zero ArbitraryStepPolicy must freeze weights ON DEVICE —
    proving the schedule is applied inside the compiled step, not by
    host-side lr mutation between dispatches."""
    policy = ArbitraryStepPolicy([(0.0, 1)])
    prng.seed_all(99)
    from veles.znicz_tpu.models import mnist
    root.mnist.decision.max_epochs = 2
    wf = mnist.create_workflow(name="LrFreeze")
    wf.link_lr_adjuster(policy)
    wf.initialize(device="cpu")
    w0 = numpy.array(wf.forwards[0].weights.map_read().mem)
    wf.run()
    w1 = wf.forwards[0].weights.map_read().mem
    assert numpy.array_equal(w0, w1), "zero-lr schedule did not freeze"


def test_schedule_survives_chunked_dispatch():
    """Chunked multi-epoch dispatch must produce the same schedule as
    per-epoch dispatch (the counter lives in traced state)."""
    def run(chunk):
        prng.seed_all(5150)
        from veles.znicz_tpu.models import mnist
        saved = {k: getattr(root.mnist.loader, k, None)
                 for k in ("minibatch_size", "n_train", "n_valid")}
        root.mnist.loader.update({"minibatch_size": 20,
                                  "n_train": 100, "n_valid": 40})
        root.mnist.decision.max_epochs = 4
        try:
            wf = mnist.create_workflow(name="LrChunk%d" % chunk)
            wf.link_lr_adjuster({"name": "exp", "gamma": 0.98})
            wf.initialize(device="cpu")
            wf.xla_step.epochs_per_dispatch = chunk
            wf.run()
        finally:
            root.mnist.loader.update(
                {k: v for k, v in saved.items() if v is not None})
        return wf.decision.history

    h1, h4 = run(1), run(4)
    for a, b in zip(h1, h4):
        assert a["validation"]["metric"] == b["validation"]["metric"]
        assert abs(a["train"]["loss"] - b["train"]["loss"]) < 1e-5


@pytest.mark.parametrize("backend", ["numpy", "cpu"])
def test_rollback_on_blowup(backend):
    """A deliberately divergent lr triggers NNRollback: weights return
    to the stashed copy and learning rates are cut."""
    prng.seed_all(31337)
    from veles.znicz_tpu.models import mnist
    saved = {k: getattr(root.mnist.loader, k, None)
             for k in ("minibatch_size", "n_train", "n_valid")}
    root.mnist.loader.update({"minibatch_size": 20,
                              "n_train": 100, "n_valid": 40})
    root.mnist.decision.max_epochs = 6
    try:
        wf = mnist.create_workflow(name="Rollback_%s" % backend)
        # epoch 1 trains sanely; then the lr explodes via a schedule
        # step so a later epoch diverges
        wf.link_lr_adjuster(ArbitraryStepPolicy([(0.02, 5), (60.0, 1)]))
        rb = wf.link_rollback(lr_cut=0.25, blowup_factor=2.0)
        wf.initialize(device=backend)
        with numpy.errstate(all="ignore"):
            wf.run()
    finally:
        root.mnist.loader.update(
            {k: v for k, v in saved.items() if v is not None})
    assert rb.rollback_count >= 1, "no rollback despite lr blow-up"
    # restored weights are the finite stash, not the diverged values
    w = wf.forwards[0].weights.map_read().mem
    assert numpy.isfinite(w).all()
    # the EFFECTIVE lr was cut via lr_scale (the policy replaces the
    # base lr, so cutting learning_rate alone would be a no-op)
    assert wf.gds[0].lr_scale == pytest.approx(
        0.25 ** rb.rollback_count)
    assert wf.gds[0].learning_rate == pytest.approx(0.02)


def test_rollback_bounds_epoch_fusion():
    """An NNRollback in the graph must cap multi-epoch dispatch fusion
    at its check interval."""
    prng.seed_all(2020)
    from veles.znicz_tpu.models import mnist
    root.mnist.decision.max_epochs = 3
    wf = mnist.create_workflow(name="RollbackChunk")
    wf.link_rollback(interval=1)
    wf.initialize(device="cpu")
    wf.xla_step.epochs_per_dispatch = 8   # forced, but must be clipped
    wf.run()
    assert wf.xla_step._chunk_len == 1
    assert len(wf.decision.history) == 3
