"""Transformer unit pairs: forward parity, jax.grad oracle on the
hand-written backwards, and LM sample convergence (config #5)."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root
from veles.memory import Array
from veles.znicz_tpu.ops.attention import (
    MultiHeadAttention, TransformerFFN, TokenDense)
from veles.znicz_tpu.ops.layernorm import LayerNormForward
from veles.znicz_tpu.ops.embedding import EmbeddingForward

from tests.test_conv_stack import (
    build, xla_forward, xla_backward, grad_oracle)


SEQ_CASES = [
    (LayerNormForward, dict()),
    (TokenDense, dict(output_features=12)),
    (TransformerFFN, dict(hidden=20)),
    (TransformerFFN, dict(hidden=20, residual=False)),
    (MultiHeadAttention, dict(heads=2)),
    (MultiHeadAttention, dict(heads=4, causal=False, residual=False)),
]


@pytest.mark.parametrize("cls,kwargs", SEQ_CASES,
                         ids=lambda v: getattr(v, "__name__", str(v)))
def test_seq_forward_parity(cls, kwargs):
    wf, feed, fwd, gd, x, err, comp = build(
        cls, input_shape=(2, 6, 8), gd_kwargs={}, **kwargs)
    golden = numpy.array(fwd.output.mem)
    y = xla_forward(comp, feed, fwd, comp.gather_params(), x)
    assert numpy.allclose(numpy.asarray(y), golden, atol=3e-5), \
        numpy.abs(numpy.asarray(y) - golden).max()


@pytest.mark.parametrize("cls,kwargs", SEQ_CASES,
                         ids=lambda v: getattr(v, "__name__", str(v)))
def test_seq_backward_vs_jax_grad(cls, kwargs):
    wf, feed, fwd, gd, x, err, comp = build(
        cls, input_shape=(2, 6, 8), gd_kwargs={}, **kwargs)
    params0 = comp.gather_params()
    state0 = comp.gather_state()
    gd.numpy_run()
    ei_np = numpy.array(gd.err_input.mem)
    ei_x, params1 = xla_backward(comp, feed, fwd, gd, params0, state0,
                                 x, err)
    gp, gx = grad_oracle(comp, feed, fwd, params0, x, err)
    assert numpy.allclose(ei_np, numpy.asarray(gx), atol=3e-4), \
        numpy.abs(ei_np - numpy.asarray(gx)).max()
    assert numpy.allclose(ei_np, numpy.asarray(ei_x), atol=3e-4)
    # every parameter's update must equal -lr*grad (lr=1, moment=0)
    for pname, grad_tree in gp.get(fwd.name, {}).items():
        w0 = numpy.array(params0[fwd.name][pname])
        w1_np = getattr(fwd, pname).map_read().mem
        w1_x = numpy.asarray(params1[fwd.name][pname])
        oracle = numpy.asarray(grad_tree)
        assert numpy.allclose(w0 - w1_np, oracle, atol=5e-4), pname
        assert numpy.allclose(w0 - w1_x, oracle, atol=5e-4), pname


def test_embedding_backward():
    import jax
    wf, feed, fwd, gd, x, err, comp = build(
        EmbeddingForward, input_shape=(3, 5),
        gd_kwargs={}, vocab_size=11, dim=7)
    # ids input: regenerate as ints
    ids = numpy.array([[1, 2, 3, 1, 0], [4, 4, 4, 4, 4],
                       [10, 9, 8, 7, 6]], numpy.int32)
    feed.minibatch_data.mem = ids
    fwd.numpy_run()
    err = prng.get("emb").normal(0, 1.0, fwd.output.shape)
    gd.err_output = Array(err)
    params0 = comp.gather_params()
    gd.numpy_run()

    # params-only jax.grad oracle (ids are not differentiable)
    import jax
    import jax.numpy as jnp
    from veles.accelerated_units import FlowContext

    def loss(p):
        ctx = FlowContext(comp, dict(p), {}, {},
                          jax.random.PRNGKey(7), True)
        ctx.set(feed, "minibatch_data", ids)
        fwd.xla_run(ctx)
        return jnp.sum(jnp.asarray(err) * ctx.get(fwd, "output"))

    gp = jax.grad(loss)(params0)
    grad_np = numpy.array(params0[fwd.name]["weights"]) \
        - fwd.weights.map_read().mem
    assert numpy.allclose(grad_np,
                          numpy.asarray(gp[fwd.name]["weights"]),
                          atol=2e-4)


def run_lm(backend):
    prng.seed_all(4242)
    from veles.znicz_tpu.models import transformer_lm
    root.lm.loader.update({"minibatch_size": 32, "n_train": 512,
                           "n_valid": 128, "seq_len": 16, "vocab": 8,
                           "max_period": 4})
    root.lm.model.update({"dim": 32, "heads": 2, "layers": 1,
                          "ffn_hidden": 64})
    root.lm.decision.max_epochs = 8
    wf = transformer_lm.create_workflow(name="LM_%s" % backend)
    wf.initialize(device=backend)
    wf.run()
    return wf


@pytest.fixture(scope="module")
def lm_numpy():
    return run_lm("numpy")


def test_lm_converges_numpy(lm_numpy):
    hist = [h["validation"]["metric"]
            for h in lm_numpy.decision.history]
    # metric = wrong TOKENS per sequence (seq_len 16). Random guessing
    # gives 14; only the first period (~2-3 tokens) is irreducibly
    # unpredictable, so a trained model lands well under 2.
    assert hist[-1] < 2.0, hist
    assert hist[-1] < hist[0] / 2, hist


def test_lm_xla_matches(lm_numpy):
    wf = run_lm("cpu")
    err_np = lm_numpy.decision.history[-1]["validation"]["metric"]
    err_x = wf.decision.history[-1]["validation"]["metric"]
    assert err_x < 2.0, err_x
    assert abs(err_np - err_x) < 0.75, (err_np, err_x)
