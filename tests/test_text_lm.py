"""Character-level text-corpus LM: loader windows/vocab, training on
a real file, and text generation round-trip."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root
from veles.znicz_tpu.generate import generate


CORPUS = ("the quick brown fox jumps over the lazy dog. " * 60)


@pytest.fixture()
def corpus_file(tmp_path):
    path = tmp_path / "corpus.txt"
    path.write_text(CORPUS)
    return str(path)


def _train_text_lm(path, name, epochs=14):
    prng.seed_all(321)
    from veles.znicz_tpu.models import transformer_lm
    saved_loader = root.lm.loader.to_dict()
    saved_model = root.lm.model.to_dict()
    saved_train = root.lm.train.to_dict()
    saved_epochs = root.lm.decision.get("max_epochs")
    root.lm.loader.update({"minibatch_size": 32, "seq_len": 24,
                           "text_file": path, "valid_ratio": 0.1})
    root.lm.model.update({"dim": 48, "heads": 2, "layers": 2,
                          "ffn_hidden": 96, "moe_experts": 0,
                          "attn_block": None, "attn_impl": None,
                          "stacked": False})
    root.lm.train.update({"solver": "adam", "learning_rate": 0.01,
                          "gradient_moment": 0.9,
                          "weights_decay": 0.0})
    root.lm.decision.max_epochs = epochs
    root.lm.parallel.update({"seq": 1, "model": 1, "data": 1,
                             "expert": 1, "pipe": 1})
    try:
        wf = transformer_lm.create_workflow(name=name)
        wf.initialize(device="xla")
        wf.run()
    finally:
        root.lm.loader.update(dict(saved_loader, text_file=None))
        root.lm.model.update(saved_model)
        root.lm.train.update({"solver": "momentum"})
        root.lm.train.update(saved_train)
        root.lm.decision.max_epochs = saved_epochs
    return wf


def test_text_loader_windows(corpus_file, tmp_path):
    """Vocab is the sorted character set; windows are next-char
    shifted; validation is the corpus tail."""
    from veles.znicz_tpu.models.transformer_lm import (
        TextLMLoader, text_vocab)
    itos, stoi = text_vocab(corpus_file)
    assert itos == sorted(set(CORPUS))
    prng.seed_all(1)
    saved = root.lm.loader.to_dict()
    root.lm.loader.update({"minibatch_size": 8, "seq_len": 24,
                           "text_file": corpus_file})
    try:
        from veles.workflow import Workflow
        wf = Workflow(None, name="TextWf")
        loader = TextLMLoader(wf, name="loader", minibatch_size=8)
        loader.load_data()
    finally:
        root.lm.loader.update(dict(saved, text_file=None))
    data = loader.original_data.mem
    labels = loader.original_labels.mem
    assert (data[:, 1:] == labels[:, :-1]).all()   # shift by one
    text0 = loader.decode(data[loader.class_lengths[1]])
    assert text0 in CORPUS                          # a real window
    assert loader.decode(loader.encode("fox")[0]) == "fox"


def test_text_lm_trains_and_generates(corpus_file):
    """The char LM learns the corpus (validation loss well under the
    uniform-vocab baseline) and continues text plausibly."""
    wf = _train_text_lm(corpus_file, "TextLM")
    hist = [h["validation"]["metric"] for h in wf.decision.history]
    assert hist[-1] < hist[0] * 0.6, hist
    wf.xla_step.sync_host()
    loader = wf.loader
    prompt = loader.encode("the quick brown ")
    out = generate(wf, prompt, 12, temperature=0.0)
    text = loader.decode(out[0])
    # greedy continuation of a memorized corpus: next chars are "fox "
    assert text.startswith("fox"), repr(text)


def test_adam_lm_snapshot_resume_generate(corpus_file, tmp_path):
    """The full user journey: train with adam → snapshot → resume in a
    FRESH workflow (adam moments restored bit-exact) → generation from
    the resumed model matches the original."""
    import os
    from veles.snapshotter import load_snapshot

    wf = _train_text_lm(corpus_file, "SnapTextLM", epochs=10)
    wf.link_snapshotter(directory=str(tmp_path))
    wf.snapshotter.run()            # snapshot the current best state
    assert os.path.exists(wf.snapshotter.destination)
    wf.xla_step.sync_host()
    prompt = wf.loader.encode("the quick brown ")
    want = generate(wf, prompt, 10, temperature=0.0)

    state = load_snapshot(wf.snapshotter.destination)
    # adam second moments really in the snapshot
    gd_states = [v for v in state["state"].values()
                 if "sq_weights" in v]
    assert gd_states and any(
        numpy.abs(v["sq_weights"]).max() > 0 for v in gd_states)

    wf2 = _train_text_lm(corpus_file, "SnapTextLM2", epochs=1)
    wf2.restore_state(state)
    for gd in wf2.gds:
        if gd.sq_weights:
            assert gd.sq_weights.map_read().mem.any()
    wf2.xla_step.refresh_device()
    got = generate(wf2, prompt, 10, temperature=0.0)
    assert (got == want).all(), (got, want)
