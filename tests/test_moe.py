"""MoE FFN unit pair: numpy↔XLA parity, jax.grad oracle (including
the analytic load-balancing term), capacity-drop semantics, and
expert parallelism on the virtual 8-device mesh."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root
from veles.memory import Array
from veles.znicz_tpu.ops.moe import MoEFFN

from tests.test_conv_stack import (
    build, xla_forward, xla_backward, grad_oracle)


MOE_CASES = [
    (MoEFFN, dict(experts=4, hidden=16)),
    (MoEFFN, dict(experts=2, hidden=8, residual=False)),
    (MoEFFN, dict(experts=4, hidden=16, capacity_factor=8.0)),
]


@pytest.mark.parametrize("cls,kwargs", MOE_CASES,
                         ids=lambda v: str(v)[:40])
def test_moe_forward_parity(cls, kwargs):
    wf, feed, fwd, gd, x, err, comp = build(
        cls, input_shape=(2, 6, 8), gd_kwargs={}, **kwargs)
    golden = numpy.array(fwd.output.mem)
    y = xla_forward(comp, feed, fwd, comp.gather_params(), x)
    assert numpy.allclose(numpy.asarray(y), golden, atol=3e-5), \
        numpy.abs(numpy.asarray(y) - golden).max()


@pytest.mark.parametrize("cls,kwargs", MOE_CASES,
                         ids=lambda v: str(v)[:40])
def test_moe_backward_vs_jax_grad(cls, kwargs):
    wf, feed, fwd, gd, x, err, comp = build(
        cls, input_shape=(2, 6, 8), gd_kwargs={}, **kwargs)
    params0 = comp.gather_params()
    state0 = comp.gather_state()
    gd.numpy_run()
    ei_np = numpy.array(gd.err_input.mem)
    ei_x, params1 = xla_backward(comp, feed, fwd, gd, params0, state0,
                                 x, err)
    gp, gx = grad_oracle(comp, feed, fwd, params0, x, err)
    assert numpy.allclose(ei_np, numpy.asarray(gx), atol=3e-4), \
        numpy.abs(ei_np - numpy.asarray(gx)).max()
    assert numpy.allclose(ei_np, numpy.asarray(ei_x), atol=3e-4)
    for pname, grad_tree in gp.get(fwd.name, {}).items():
        w0 = numpy.array(params0[fwd.name][pname])
        w1_np = getattr(fwd, pname).map_read().mem
        w1_x = numpy.asarray(params1[fwd.name][pname])
        oracle = numpy.asarray(grad_tree)
        assert numpy.allclose(w0 - w1_np, oracle, atol=5e-4), pname
        assert numpy.allclose(w0 - w1_x, oracle, atol=5e-4), pname


def test_moe_aux_loss_gradient_matches_jax():
    """The analytic Switch load-balancing gradient == jax.grad of the
    explicit aux loss aux_w·E·Σ_e f_e·mean_t(probs) (f constant)."""
    import jax
    import jax.numpy as jnp
    from veles.accelerated_units import FlowContext

    aux_w = 0.37
    wf, feed, fwd, gd, x, err, comp = build(
        MoEFFN, input_shape=(2, 6, 8), gd_kwargs=dict(aux_weight=aux_w),
        experts=4, hidden=16)
    params0 = comp.gather_params()
    gd.numpy_run()
    grad_router_np = (numpy.array(params0[fwd.name]["router"])
                      - fwd.router.map_read().mem)

    def loss(p):
        ctx = FlowContext(comp, dict(p), {}, {},
                          jax.random.PRNGKey(7), True)
        ctx.set(feed, "minibatch_data", x)
        fwd.xla_run(ctx)
        y = ctx.get(fwd, "output")
        probs = ctx.get(fwd, "cache_probs")
        onehot = jax.lax.stop_gradient(ctx.get(fwd, "cache_onehot_e"))
        aux = aux_w * fwd.experts * jnp.sum(
            onehot.mean(axis=0) * probs.mean(axis=0))
        return jnp.sum(jnp.asarray(err) * y) + aux

    gp = jax.grad(loss)(params0)
    oracle = numpy.asarray(gp[fwd.name]["router"])
    assert numpy.allclose(grad_router_np, oracle, atol=5e-4), \
        numpy.abs(grad_router_np - oracle).max()


def test_moe_capacity_drop():
    """With capacity 1 per expert, overflow tokens must bypass the
    experts: residual-only output, and brute-force per-token routing
    reproduces the unit's output exactly."""
    wf, feed, fwd, gd, x, err, comp = build(
        MoEFFN, input_shape=(1, 8, 8), gd_kwargs={},
        experts=2, hidden=8, capacity_factor=0.25)  # cap = 1
    cap = fwd.capacity(8)
    assert cap == 1
    xt = x.reshape(-1, 8).astype(numpy.float32)
    r = fwd.router.mem
    logits = xt @ r
    probs = numpy.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    eidx = logits.argmax(-1)
    seen = {e: 0 for e in range(fwd.experts)}
    golden = numpy.array(xt)  # residual
    for t in range(xt.shape[0]):
        e = int(eidx[t])
        if seen[e] >= cap:
            continue          # dropped token: residual only
        seen[e] += 1
        h = numpy.maximum(xt[t] @ fwd.weights.mem[e] + fwd.bias.mem[e],
                          0.0)
        golden[t] += probs[t, e] * (h @ fwd.weights2.mem[e]
                                    + fwd.bias2.mem[e])
    assert numpy.allclose(fwd.output.mem.reshape(-1, 8), golden,
                          atol=1e-5)
    # at least one token must actually have overflowed for the test to
    # mean anything (8 tokens, 2 experts, capacity 1 ⇒ ≥6 dropped)
    assert sum(seen.values()) == 2


EXTRA_UNIT_CASES = [
    ("ffn", ("weights2",), ("bias2",)),
    ("mha", ("weights_out",), ("bias_out",)),
    ("moe", ("weights2", "router"), ("bias2",)),
]


@pytest.mark.parametrize("kind,wlike,blike", EXTRA_UNIT_CASES,
                         ids=[c[0] for c in EXTRA_UNIT_CASES])
def test_extra_param_accumulation_and_bias_hypers(kind, wlike, blike):
    """Units with parameters beyond weights/bias must give them the
    same semantics: gradient accumulation holds ALL updates until the
    accumulation boundary, weight-like extras use the weight hyper set
    (decay applies), bias-like extras use the bias set (no decay by
    default) — and the traced path matches the oracle."""
    import jax
    from veles.accelerated_units import FlowContext
    from veles.znicz_tpu.ops.attention import (
        TransformerFFN, MultiHeadAttention)

    cls, kwargs = {
        "ffn": (TransformerFFN, dict(hidden=16)),
        "mha": (MultiHeadAttention, dict(heads=2)),
        "moe": (MoEFFN, dict(experts=2, hidden=8)),
    }[kind]
    lr, l2 = 0.5, 0.2
    wf, feed, fwd, gd, x, err, comp = build(
        cls, input_shape=(2, 4, 8),
        gd_kwargs=dict(accumulate_gradient=2, learning_rate=lr,
                       weights_decay=l2, gradient_moment=0.0),
        **kwargs)
    # zero error ⇒ zero gradients; only L2 decay can move parameters
    zero_err = numpy.zeros_like(err)
    gd.err_output = Array(zero_err)
    p0 = {n: numpy.array(getattr(fwd, n).mem) for n in fwd.PARAMS}
    params0 = comp.gather_params()
    state0 = comp.gather_state()

    gd.numpy_run()
    for n in fwd.PARAMS:   # step 1 of 2: nothing applies anywhere
        assert numpy.allclose(getattr(fwd, n).mem, p0[n]), n
    fwd.numpy_run()
    gd.numpy_run()
    for n in wlike + ("weights",):   # step 2: weight-set decay applies
        expect = p0[n] * (1.0 - lr * l2)
        assert numpy.allclose(getattr(fwd, n).mem, expect,
                              atol=1e-6), n
    for n in blike + ("bias",):      # bias set: no decay by default
        assert numpy.allclose(getattr(fwd, n).mem, p0[n]), n

    # traced twin over the same two steps
    def fn(p, s, xv, ev):
        ctx = FlowContext(comp, dict(p), dict(s),
                          {gd.name: gd.hyperparams()},
                          jax.random.PRNGKey(7), True)
        ctx.set(feed, "minibatch_data", xv)
        fwd.xla_run(ctx)
        ctx.set(gd, "err_output", ev)
        gd.xla_run(ctx)
        return ctx.params, ctx.state

    step = jax.jit(fn)
    p, s = step(params0, state0, x, zero_err)
    p, s = step(p, s, x, zero_err)
    for n in fwd.PARAMS:
        assert numpy.allclose(numpy.asarray(p[fwd.name][n]),
                              getattr(fwd, n).mem, atol=1e-6), n


def _run_moe_lm(backend, parallel_spec=None, seed=515,
                capacity_factor=2.0, max_epochs=6):
    prng.seed_all(seed)
    from veles.znicz_tpu.models import transformer_lm
    root.lm.loader.update({"minibatch_size": 32, "n_train": 512,
                           "n_valid": 128, "seq_len": 16, "vocab": 8,
                           "max_period": 4})
    root.lm.model.update({"dim": 32, "heads": 2, "layers": 1,
                          "ffn_hidden": 64, "moe_experts": 4,
                          "moe_capacity_factor": capacity_factor,
                          "moe_aux_weight": 0.01, "attn_block": None})
    root.lm.decision.max_epochs = max_epochs
    root.lm.parallel.update({"seq": 1, "model": 1, "data": 1,
                             "expert": 1, "ep_routing": "gather"})
    if parallel_spec:
        root.lm.parallel.update(parallel_spec)
    wf = transformer_lm.create_workflow(
        name="MoELM_%s_%s" % (backend, parallel_spec))
    wf.initialize(device=backend)
    wf.run()
    # don't leak MoE/EP config into other test modules
    root.lm.model.moe_experts = 0
    root.lm.parallel.update({"seq": 1, "model": 1, "data": 1,
                             "expert": 1, "ep_routing": "gather"})
    return wf


def test_moe_lm_trains_and_ep_matches_single_device():
    """The MoE LM must train (error drops), and expert-sharding the
    same model over the mesh must reproduce the single-device run."""
    wf1 = _run_moe_lm("xla")
    h1 = [e["validation"]["metric"] for e in wf1.decision.history]
    assert h1[-1] < h1[0], h1
    wf8 = _run_moe_lm("xla", {"expert": 4, "data": 2})
    h8 = [e["validation"]["metric"] for e in wf8.decision.history]
    # same data, same seeds, same math — EP/DP is a layout choice, so
    # histories agree to float tolerance
    assert numpy.allclose(h1, h8, atol=1e-2), (h1, h8)
    # params really live expert-sharded on the mesh
    step = wf8.xla_step
    moe_units = [f for f in wf8.forwards
                 if type(f).__name__ == "MoEFFN"]
    assert moe_units
    leaf = step.params[moe_units[0].name]["weights"]
    assert len(leaf.sharding.device_set) == 8
    spec = leaf.sharding.spec
    assert spec and spec[0] == "expert", spec
    # gather-based EP in the partitioned HLO: tokens reach the
    # one-expert-per-device shards via all-gather (GSPMD's lowering of
    # the one-hot dispatch einsum at these shapes), gradients
    # all-reduce over data — proves distribution, not replication
    from veles.znicz_tpu import parallel
    parallel.assert_collectives(step, ["all-gather", "all-reduce"])


def test_moe_lm_ep_alltoall_matches_single_device():
    """The explicit shard_map all-to-all EP (parallel/expert.py) is a
    layout choice too: with a capacity factor high enough that no
    token overflows a per-shard quota, EP4 and EP4xDP2 reproduce the
    single-device run, and the exchange really lowers to all-to-all
    ops in the partitioned HLO (the gather mode's O(E)-bandwidth
    all-gather must be gone from the token path)."""
    from veles.znicz_tpu import parallel

    wf1 = _run_moe_lm("xla", capacity_factor=8.0)
    h1 = [e["validation"]["metric"] for e in wf1.decision.history]
    wf4 = _run_moe_lm("xla", {"expert": 4, "ep_routing": "alltoall"},
                      capacity_factor=8.0)
    h4 = [e["validation"]["metric"] for e in wf4.decision.history]
    assert numpy.allclose(h1, h4, atol=1e-3), (h1, h4)
    counts = parallel.assert_collectives(wf4.xla_step, ["all-to-all"])
    # ...and the O(E) token replication really is gone: this program
    # has no all-gather at all (the gather mode shows several)
    assert not counts.get("all-gather"), counts
    # DP on top: tokens shard over (data, expert); grads all-reduce
    wf8 = _run_moe_lm("xla", {"expert": 4, "data": 2,
                              "ep_routing": "alltoall"},
                      capacity_factor=8.0)
    h8 = [e["validation"]["metric"] for e in wf8.decision.history]
    assert numpy.allclose(h1, h8, atol=1e-3), (h1, h8)
    counts8 = parallel.assert_collectives(wf8.xla_step,
                                          ["all-to-all", "all-reduce"])
    assert not counts8.get("all-gather"), counts8
    # params stay expert-sharded exactly like gather mode
    moe_units = [f for f in wf8.forwards
                 if type(f).__name__ == "MoEFFN"]
    leaf = wf8.xla_step.params[moe_units[0].name]["weights"]
    spec = leaf.sharding.spec
    assert spec and spec[0] == "expert", spec


def test_moe_lm_ep_alltoall_composes_with_sp_tp():
    """alltoall EP shards tokens over EVERY mesh axis inside the
    exchange (round 4 follow-up: the extra axes are additional token
    shards, expert/router grads psum back over them), so it composes
    with ring-SP and TP instead of raising. Parity at non-overflowing
    capacity vs the single-device run, with the exchange AND the
    companion collective both in the partitioned HLO."""
    from veles.znicz_tpu import parallel
    wf1 = _run_moe_lm("xla", capacity_factor=8.0)
    h1 = [e["validation"]["metric"] for e in wf1.decision.history]
    wf_sp = _run_moe_lm("xla", {"expert": 4, "seq": 2,
                                "ep_routing": "alltoall"},
                        capacity_factor=8.0)
    hsp = [e["validation"]["metric"] for e in wf_sp.decision.history]
    assert numpy.allclose(h1, hsp, atol=1e-3), (h1, hsp)
    parallel.assert_collectives(
        wf_sp.xla_step, ["all-to-all", "collective-permute"])
    wf_tp = _run_moe_lm("xla", {"expert": 4, "model": 2,
                                "ep_routing": "alltoall"},
                        capacity_factor=8.0)
    htp = [e["validation"]["metric"] for e in wf_tp.decision.history]
    assert numpy.allclose(h1, htp, atol=1e-3), (h1, htp)
    parallel.assert_collectives(wf_tp.xla_step, ["all-to-all"])


def test_ep_alltoall_overflow_drop_pattern():
    """The OVERFLOW regime contract (parallel/expert.py docstring,
    VERDICT r4 #6): alltoall mode enforces ``ceil(cf·T_loc/E)`` PER
    SOURCE SHARD, the single-chip/gather formulation one global
    ``ceil(cf·T/E)`` quota — so the drop pattern diverges in BOTH
    directions. Constructed routing on a 4-shard expert mesh: every
    kept/dropped token is pinned against a brute-force rank oracle for
    each quota, and the two divergence directions are both present:

    * a token KEPT by its per-shard quota but over the global quota
      (an expert fed by many shards: each shard's rank fits, the
      global queue overflows);
    * a token DROPPED by its per-shard quota but within the global
      one (a shard skewed toward one expert overflows its local
      quota while the expert's global queue has room)."""
    import jax
    import jax.numpy as jnp
    from veles.znicz_tpu import parallel
    from veles.znicz_tpu.parallel import expert as EP

    E = D = 4
    B, S, H = 4, 4, 8          # 4 shards (expert axis) x 4 tokens
    mesh = parallel.make_mesh({"expert": E}, jax.devices("cpu")[:E])
    cf = 0.5
    t_loc, t_glob = S, B * S   # one batch row per shard
    cap_loc = max(1, int(numpy.ceil(cf * t_loc / E)))    # = 1
    cap_glob = max(1, int(numpy.ceil(cf * t_glob / E)))  # = 2
    assert (cap_loc, cap_glob) == (1, 2)
    # shard s routes its tokens to these experts (token order = global
    # order within the row): expert 0 gets ONE token from every shard
    # (per-shard rank 0 everywhere, global queue length 4 > 2);
    # shard 0 sends TWO tokens to expert 1 (local rank 1 >= 1 drops
    # the second, global queue length 2 fits)
    route = numpy.array([[0, 1, 1, 2],
                         [0, 2, 2, 3],
                         [0, 3, 3, 2],
                         [0, 1, 3, 2]], numpy.int32)
    x = numpy.zeros((B, S, D), numpy.float32)
    for b in range(B):
        for s in range(S):
            x[b, s, route[b, s]] = 5.0   # router=I -> argmax routing

    class _Unit:
        experts = E
        ACTIVATION = "strict_relu"
        residual = False
        ep_mesh = mesh
        ep_axis = "expert"
        ep_batch_axes = ()

        @staticmethod
        def capacity(n_tokens):
            return max(1, int(numpy.ceil(cf * n_tokens / E)))

    gen = prng.get("ep_overflow")
    params = {
        "router": jnp.asarray(numpy.eye(D, E, dtype=numpy.float32)),
        "weights": jnp.asarray(
            gen.normal(0, 0.3, (E, D, H)).astype(numpy.float32)),
        "bias": jnp.zeros((E, H), jnp.float32),
        "weights2": jnp.asarray(
            gen.normal(0, 0.3, (E, H, D)).astype(numpy.float32)),
        "bias2": jnp.zeros((E, D), jnp.float32),
    }
    es = lambda spec, *ops: jnp.einsum(spec, *ops)
    _y, cache = EP.moe_a2a_fwd(jnp.asarray(x), params, _Unit, es)
    kept_a2a = numpy.asarray(
        cache["dispatch"]).sum(axis=(-1, -2)).reshape(B, S) > 0.5

    def rank_keep(eidx_seq, cap):
        """keep mask under a single quota: rank within the expert's
        arrival queue (rank counts every routed token, kept or not —
        the cumsum formula in ops/moe.py route_tokens)."""
        cnt = {}
        keep = []
        for e in eidx_seq:
            keep.append(cnt.get(e, 0) < cap)
            cnt[e] = cnt.get(e, 0) + 1
        return numpy.array(keep)

    # per-shard oracle: each shard ranks ITS tokens only
    kept_shard = numpy.stack(
        [rank_keep(route[b], cap_loc) for b in range(B)])
    # global oracle: one queue over all tokens in global order — the
    # single-chip / gather-mode quota (route_tokens with cap_glob)
    kept_glob = rank_keep(route.reshape(-1), cap_glob).reshape(B, S)
    assert numpy.array_equal(kept_a2a, kept_shard), \
        (kept_a2a, kept_shard)
    # both divergence directions really occur in this construction
    assert numpy.any(kept_a2a & ~kept_glob)    # kept local, over glob
    assert numpy.any(~kept_a2a & kept_glob)    # dropped local only
    # ...and the gather/single-chip formula really produces the global
    # pattern (shared route_tokens with the global cap)
    from veles.znicz_tpu.ops import moe
    _, _, _, dispatch_g = moe.route_tokens(
        numpy, x.reshape(-1, D), numpy.eye(D, E, dtype=numpy.float32),
        E, cap_glob)
    kept_gather = dispatch_g.sum(axis=(-1, -2)).reshape(B, S) > 0.5
    assert numpy.array_equal(kept_gather, kept_glob)
    # dropped tokens bypass the experts entirely: residual=False makes
    # their combined output exactly zero
    y = numpy.asarray(_y).reshape(B, S, D)
    out_norm = numpy.abs(y).sum(axis=-1)
    assert numpy.all(out_norm[~kept_a2a] == 0.0)
    assert numpy.all(out_norm[kept_a2a] > 0.0)


def test_moe_lm_ep_alltoall_trains_with_drops():
    """At the default tight capacity (per-SHARD quotas differ from the
    single-chip global quota, so no exact parity claim) the a2a path
    still trains: error drops and the HLO carries the exchange."""
    from veles.znicz_tpu import parallel
    wf = _run_moe_lm("xla", {"expert": 4, "ep_routing": "alltoall"})
    h = [e["validation"]["metric"] for e in wf.decision.history]
    assert h[-1] < h[0], h
    parallel.assert_collectives(wf.xla_step, ["all-to-all"])


def test_moe_ep_alltoall_snapshot_restores_single_device(tmp_path):
    """Checkpoints are LAYOUT-independent: a snapshot written while
    the experts were sharded over the mesh (alltoall routing) restores
    bit-for-bit onto a plain single-device workflow — the distributed
    run leaves nothing layout-specific in the checkpoint."""
    from veles.snapshotter import Snapshotter, load_snapshot

    wf = _run_moe_lm("xla", {"expert": 4, "data": 2,
                             "ep_routing": "alltoall"},
                     capacity_factor=8.0)
    snap = Snapshotter(wf, name="snap", directory=str(tmp_path))
    snap.decision = wf.decision
    state = load_snapshot(snap.export_snapshot())
    wf1 = _run_moe_lm("xla", capacity_factor=8.0, seed=516,
                      max_epochs=1)
    wf1.restore_state(state)
    moe = next(f for f in wf1.forwards if isinstance(f, MoEFFN))
    for key in MoEFFN.PARAMS:
        restored = wf1.xla_step.params[moe.name][key]
        # values from the sharded checkpoint, placement single-device
        assert numpy.array_equal(
            numpy.asarray(restored),
            numpy.asarray(state["params"][moe.name][key])), key
        assert len(restored.sharding.device_set) == 1


def test_moe_lm_single_slave_matches_standalone():
    """The elastic master/slave compat path ships EVERY forward
    parameter (router/experts included): one-slave distributed
    training of the MoE LM equals sequential SGD bitwise-ish."""
    from veles.server import MasterServer
    from veles.client import SlaveClient
    from veles.loader.base import CLASS_TRAIN
    from veles.znicz_tpu.models import transformer_lm

    def make(name, seed=606):
        prng.seed_all(seed)
        root.lm.loader.update({"minibatch_size": 16, "n_train": 64,
                               "n_valid": 16, "seq_len": 8,
                               "vocab": 8, "max_period": 4})
        root.lm.model.update({"dim": 16, "heads": 2, "layers": 1,
                              "ffn_hidden": 32, "moe_experts": 2,
                              "attn_block": None, "attn_impl": None,
                              "stacked": False})
        root.lm.decision.max_epochs = 2
        root.lm.parallel.update({"seq": 1, "model": 1, "data": 1,
                                 "expert": 1, "pipe": 1})
        wf = transformer_lm.create_workflow(name=name)
        wf.initialize(device="numpy")
        wf.loader.shuffle_enabled = False
        wf.loader._start_epoch(first=True)
        return wf

    try:
        ref = make("MoERef")
        loader = ref.loader
        for _ in range(2 * loader.effective_batches_per_epoch):
            loader.run()
            for u in ref.forwards:
                u.run()
            ref.evaluator.run()
            if loader.minibatch_class == CLASS_TRAIN:
                for gd in reversed(ref.gds):
                    gd.run()
        moe_ref = [f for f in ref.forwards
                   if type(f).__name__ == "MoEFFN"][0]
        w_ref = {k: numpy.array(getattr(moe_ref, k).map_read().mem)
                 for k in moe_ref.PARAMS}

        master_wf = make("MoEMaster")
        server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2)
        server.start_background()
        addr = "127.0.0.1:%d" % server.bound_address[1]
        slave = make("MoESlave")
        slave.is_slave = True
        SlaveClient(slave, addr, name="moes1").run_forever()
        assert server.done.is_set()
        moe_m = [f for f in master_wf.forwards
                 if type(f).__name__ == "MoEFFN"][0]
        for k in moe_ref.PARAMS:   # router AND experts converged alike
            numpy.testing.assert_allclose(
                getattr(moe_m, k).map_read().mem, w_ref[k],
                atol=1e-6, err_msg=k)
    finally:
        root.lm.model.moe_experts = 0
        root.lm.parallel.update({"seq": 1, "model": 1, "data": 1,
                                 "expert": 1})
