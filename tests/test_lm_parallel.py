"""LM sharding from config alone (VERDICT item: ring/SP reachable
without touching units) + Megatron-style TP over the model axis.
Runs on the 8-device virtual CPU mesh (conftest)."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root


@pytest.fixture(autouse=True, scope="module")
def _restore_lm_config():
    import veles.znicz_tpu.models.mnist  # noqa: defaults
    import veles.znicz_tpu.models.transformer_lm  # noqa: defaults
    saved_loader = root.lm.loader.to_dict()
    saved_epochs = root.lm.decision.get("max_epochs")
    # the combo tests borrow test_service.make_wf, which mutates
    # root.mnist — this module runs BEFORE test_mnist_functional
    saved_mnist = root.mnist.loader.to_dict()
    saved_mnist_epochs = root.mnist.decision.get("max_epochs")
    yield
    root.lm.loader.update(saved_loader)
    root.lm.decision.max_epochs = saved_epochs
    root.mnist.loader.update(saved_mnist)
    root.mnist.decision.max_epochs = saved_mnist_epochs


def _run_lm(name, parallel=None, max_epochs=3):
    prng.seed_all(777)
    from veles.znicz_tpu.models import transformer_lm
    saved = root.lm.parallel.to_dict()
    root.lm.loader.update({"minibatch_size": 32, "n_train": 256,
                           "n_valid": 64})
    root.lm.decision.max_epochs = max_epochs
    root.lm.parallel.update(parallel or
                            {"seq": 1, "model": 1, "data": 1})
    try:
        wf = transformer_lm.create_workflow(name=name)
        wf.initialize(device="cpu")
        wf.run()
    finally:
        root.lm.parallel.update(saved)
    return wf


@pytest.fixture(scope="module")
def dense_wf():
    return _run_lm("LMDense")


def _history(wf):
    return [h["validation"]["metric"] for h in wf.decision.history]


def test_lm_dense_learns(dense_wf):
    hist = _history(dense_wf)
    assert hist[-1] < hist[0], hist


def test_lm_ring_from_config(dense_wf):
    """root.lm.parallel.seq=8 routes attention through the ppermute
    ring; same seeds => same training trajectory as dense attention
    (ring softmax is numerically exact up to fp reassociation)."""
    wf = _run_lm("LMRing", {"seq": 8})
    from veles.znicz_tpu.ops.attention import MultiHeadAttention
    mha = [f for f in wf.forwards
           if isinstance(f, MultiHeadAttention)]
    assert mha and all(f.seq_mesh is not None for f in mha), \
        "config did not engage the ring path"
    ring, dense = _history(wf), _history(dense_wf)
    assert ring[-1] < ring[0]
    for a, b in zip(ring, dense):
        assert abs(a - b) < 0.05, (ring, dense)
    # the ring's neighbour hops must survive into the partitioned HLO
    from veles.znicz_tpu import parallel
    parallel.assert_collectives(wf.xla_step, ["collective-permute"])


def test_lm_ring_flash_inner_from_config(dense_wf):
    """root.lm.parallel.seq=4 + root.lm.model.attn_impl="scan" runs
    every ring step's LOCAL block through the flash kernels
    (parallel/ring.py inner-block composition, round 4); training
    trajectory still matches dense. (The Pallas inner is
    parity-tested at function level in test_parallel.py — interpret
    mode is too slow for a whole workflow.)"""
    saved_impl = root.lm.model.get("attn_impl")
    root.lm.model.attn_impl = "scan"
    try:
        wf = _run_lm("LMRingFlash", {"seq": 4})
    finally:
        root.lm.model.attn_impl = saved_impl
    from veles.znicz_tpu.ops.attention import MultiHeadAttention
    mha = [f for f in wf.forwards
           if isinstance(f, MultiHeadAttention)]
    assert mha and all(f.seq_mesh is not None for f in mha)
    # ...and the flash inner really engaged (seq_mesh alone is also
    # true for the dense-inner ring)
    assert all(f.attn_impl == "scan" for f in mha)

    class _Ctx:   # minimal resolver probe
        _compiler = wf.xla_step.compiler
    for f in mha:
        inner, block = f._ring_inner(_Ctx())
        assert inner == "scan" and block >= 1, (inner, block)
    ring, dense = _history(wf), _history(dense_wf)
    assert ring[-1] < ring[0]
    for a, b in zip(ring, dense):
        assert abs(a - b) < 0.05, (ring, dense)
    from veles.znicz_tpu import parallel
    parallel.assert_collectives(wf.xla_step, ["collective-permute"])


def test_lm_tensor_parallel_from_config(dense_wf):
    """root.lm.parallel.model=4 shards qkv/up column-wise and out/down
    row-wise; GSPMD inserts the collectives. Same math => same
    trajectory as the unsharded run."""
    wf = _run_lm("LMTP", {"model": 4})
    step = wf.xla_step
    assert step.param_sharding_map, "TP sharding map not installed"
    # params are REALLY sharded on the mesh
    import jax
    from veles.znicz_tpu.ops.attention import TransformerFFN
    ffn = next(f for f in wf.forwards if isinstance(f, TransformerFFN))
    leaf = step.params[ffn.name]["weights"]
    assert len(leaf.sharding.device_set) == 4
    spec = leaf.sharding.spec
    assert tuple(spec) == (None, "model"), spec
    tp, dense = _history(wf), _history(dense_wf)
    assert tp[-1] < tp[0]
    for a, b in zip(tp, dense):
        assert abs(a - b) < 0.05, (tp, dense)
    # row-sharded contractions must all-reduce in the partitioned HLO
    from veles.znicz_tpu import parallel
    parallel.assert_collectives(wf.xla_step, ["all-reduce"])


def test_lm_dp_plus_tp(dense_wf):
    """2-way data x 4-way model on one mesh."""
    wf = _run_lm("LMDPTP", {"data": 2, "model": 4})
    step = wf.xla_step
    assert step.batch_sharding is not None
    assert step.param_sharding_map
    from veles.znicz_tpu import parallel
    parallel.assert_collectives(step, ["all-reduce"])
    hist, dense = _history(wf), _history(dense_wf)
    assert hist[-1] < hist[0]
    for a, b in zip(hist, dense):
        assert abs(a - b) < 0.05, (hist, dense)


def test_lm_sp_plus_dp(dense_wf):
    """2-way data x 4-way seq on ONE composed mesh: the ring shards
    the sequence while the batch shards over data."""
    wf = _run_lm("LMSPDP", {"data": 2, "seq": 4})
    from veles.znicz_tpu.ops.attention import MultiHeadAttention
    mha = next(f for f in wf.forwards
               if isinstance(f, MultiHeadAttention))
    assert mha.seq_mesh is not None
    assert mha.seq_batch_axis == "data"
    assert dict(mha.seq_mesh.shape) == {"data": 2, "seq": 4}
    from veles.znicz_tpu import parallel
    parallel.assert_collectives(
        wf.xla_step, ["collective-permute", "all-reduce"])
    hist, dense = _history(wf), _history(dense_wf)
    assert hist[-1] < hist[0]
    for a, b in zip(hist, dense):
        assert abs(a - b) < 0.05, (hist, dense)


def test_dp_snapshot_resume_rollback_combo(tmp_path):
    """DP mesh x snapshotter x rollback together; resume re-places the
    params on the mesh."""
    import jax
    from tests.test_service import make_wf
    from veles.snapshotter import load_snapshot
    from veles.znicz_tpu import parallel

    wf = make_wf("DPSnapT", backend="cpu", snapdir=str(tmp_path))
    parallel.setup_data_parallel(wf, parallel.make_mesh({"data": 8}))
    wf.link_rollback(lr_cut=0.5, blowup_factor=50.0)
    wf.run()
    assert wf.snapshotter.destination

    state = load_snapshot(wf.snapshotter.destination)
    wf2 = make_wf("DPSnapT2", backend="cpu", max_epochs=3)
    parallel.setup_data_parallel(wf2, parallel.make_mesh({"data": 8}))
    wf2.restore_state(state)
    wf2.run()
    assert wf2.decision.epoch_number == 3
    leaf = jax.tree_util.tree_leaves(wf2.xla_step.params)[0]
    assert len(leaf.sharding.device_set) == 8


def test_tp_snapshot_resume(tmp_path, dense_wf):
    """TP-sharded LM params checkpoint and restore onto the mesh."""
    import jax
    from veles.snapshotter import load_snapshot

    wf = _run_lm("LMTPSnap", {"model": 4})
    from veles.snapshotter import Snapshotter
    snap = Snapshotter(wf, name="snap", directory=str(tmp_path))
    snap.decision = wf.decision
    path = snap.export_snapshot()
    state = load_snapshot(path)

    wf2 = _run_lm("LMTPSnap2", {"model": 4}, max_epochs=1)
    wf2.restore_state(state)
    step = wf2.xla_step
    from veles.znicz_tpu.ops.attention import TransformerFFN
    ffn = next(f for f in wf2.forwards
               if isinstance(f, TransformerFFN))
    leaf = step.params[ffn.name]["weights"]
    # restored AND still TP-sharded over the model axis
    assert len(leaf.sharding.device_set) == 4
    assert tuple(leaf.sharding.spec) == (None, "model")
    numpy.testing.assert_allclose(
        numpy.asarray(leaf),
        state["params"][ffn.name]["weights"], atol=1e-6)


def test_tp_grad_sync_accounting(dense_wf):
    """grad_sync_bytes still reports the full trainable payload."""
    from veles.znicz_tpu import parallel
    import jax
    host = jax.tree_util.tree_map(
        lambda a: numpy.asarray(a), dense_wf.xla_step.params)
    assert parallel.grad_sync_bytes(host) > 0
