"""Fleet router/autoscaler tier (ISSUE 13, ``veles/router.py``).

Unit level first (consistent-hash ring, least-queue selection,
eject/half-open transitions, autoscaler policy — all driven with
injected scrape rows, no sockets, no clock luck), then live HTTP:
stub replicas on the shared reactor behind a real
:class:`RouterFrontend`, including the end-to-end chaos acceptance
run (brownout one replica via :class:`BrownoutProxy` + a ``/readyz``
flip -> ejection within two control ticks, zero requests to the
ejected replica, one trace_id spanning client -> router -> replica,
half-open re-admission on recovery)."""

import json
import time
import urllib.error
import urllib.request

import pytest

from veles import fleet, reactor, telemetry
from veles.chaos import BrownoutProxy
from veles.router import (ADMITTED, DRAINING, EJECTED, HALF_OPEN,
                          Autoscaler, DryRunExecutor, FleetController,
                          HashRing, RouterFrontend)


def wait_until(fn, timeout=15.0, interval=0.01, what="condition"):
    """Poll ``fn`` until truthy; -> its value (asserts on timeout)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = fn()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError("timed out waiting for %s" % what)


def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.load(resp), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, json.load(exc), dict(exc.headers)


def _post(url, doc, headers=None, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers=dict({"Content-Type": "application/json"},
                     **(headers or {})))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.load(resp), dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, json.load(exc), dict(exc.headers)


# -- stub replica -------------------------------------------------------


class StubReplica:
    """A minimal serving-replica HTTP surface on the shared reactor:
    real sockets, controllable readiness / SLO firing / queue gauge /
    token pacing — the deterministic backend the router tests brown
    out and flip without touching a model."""

    def __init__(self, name, tokens=4, token_interval=0.02):
        self.model = name
        self.ready = True
        self.reasons = ["stub: flipped"]
        self.firing = []
        self.queue_rows = 0.0
        self.tokens = tokens
        self.token_interval = token_interval
        self.predicts = 0
        self.generates = 0
        self.streams_completed = 0
        self.last_headers = {}
        self.server = reactor.HttpServer(
            "127.0.0.1", 0, self._route, name="stub-" + name)
        self.url = "http://127.0.0.1:%d" % self.server.port

    def _route(self, request):
        path = request.path
        if path.startswith("/healthz"):
            request.reply_json(200, {"status": "ok"})
        elif path.startswith("/readyz"):
            if self.ready:
                request.reply_json(200, {"ready": True, "reasons": [],
                                         "checks": {}, "slos": {}})
            else:
                request.reply_json(503, {"ready": False,
                                         "reasons": list(self.reasons),
                                         "checks": {}, "slos": {}})
        elif path.startswith("/metrics"):
            lines = ['veles_serving_queue_rows{model="%s"} %g'
                     % (self.model, self.queue_rows)]
            for obj in self.firing:
                lines.append('veles_slo_alert_firing{objective="%s"} 1'
                             % obj)
            request.reply(200, ("\n".join(lines) + "\n").encode(),
                          "text/plain")
        elif path == "/v1/predict" and request.method == "POST":
            self.predicts += 1
            self.last_headers = dict(request.headers)
            request.reply_json(200, {"replica": self.model,
                                     "outputs": [[1.0]],
                                     "version": 1})
        elif path == "/v1/generate" and request.method == "POST":
            self.generates += 1
            self.last_headers = dict(request.headers)
            stream = request.begin_stream(200,
                                          "application/x-ndjson")
            state = {"n": 0}

            def emit():
                if stream.closed:
                    return
                if state["n"] < self.tokens:
                    stream.write(json.dumps(
                        {"token": state["n"]}) + "\n")
                    state["n"] += 1
                    self.server.reactor.call_later(
                        self.token_interval, emit)
                else:
                    stream.write(json.dumps(
                        {"done": True, "replica": self.model}) + "\n")
                    stream.end()
                    self.streams_completed += 1

            self.server.reactor.call_later(self.token_interval, emit)
        else:
            request.reply_json(404, {"error": "not found"})

    def close(self):
        self.server.close()


def _row(url, reachable=True, ready=True, firing=(), queue=0.0,
         reasons=()):
    """One injected fleet-scrape row (the controller's sensor input)."""
    return {"url": url, "reachable": reachable, "ready": ready,
            "firing": list(firing), "reasons": list(reasons),
            "metrics": {"serving_queue_rows": queue}}


# -- unit: ring + selection + transitions -------------------------------


def test_hash_ring_remaps_only_the_removed_backend():
    urls = ["http://a:1", "http://b:1", "http://c:1"]
    ring = HashRing(urls)
    keys = ["session:%d" % i for i in range(200)]
    before = {k: ring.lookup(k, set(urls)) for k in keys}
    assert set(before.values()) == set(urls)  # all backends used
    # ejection = ineligibility, NOT ring surgery: survivors keep
    # every key they had
    survivors = {"http://a:1", "http://c:1"}
    after = {k: ring.lookup(k, survivors) for k in keys}
    for k in keys:
        if before[k] in survivors:
            assert after[k] == before[k], k
        else:
            assert after[k] in survivors
    # sticky under membership no-ops: same key, same answer
    assert ring.lookup("session:x", set(urls)) \
        == ring.lookup("session:x", set(urls))


def test_controller_least_queue_and_eject_readmit_cycle():
    a, b = "http://a:1", "http://b:1"
    c = FleetController([a, b], interval=999.0)
    try:
        c.tick(rows=[_row(a, queue=5.0), _row(b, queue=0.0)])
        assert c.select().url == b          # least queue wins
        c.tick(rows=[_row(a, queue=0.0), _row(b, queue=5.0)])
        assert c.select().url == a

        # readiness flip ejects eagerly, requests drain to the other
        c.tick(rows=[_row(a, ready=False, reasons=["models: none"]),
                     _row(b)])
        assert c._replicas[a].state == EJECTED
        assert c.select().url == b
        assert telemetry.get_registry().counter_total(
            "veles_router_ejections_total", reason="not_ready") == 1

        # SLO burn-rate firing ejects too
        c.tick(rows=[_row(a, ready=False), _row(b, firing=["p99"])])
        assert c._replicas[b].state == EJECTED
        assert c.select() is None           # nothing admitted

        # recovery -> half-open: exactly ONE probe slot
        c.tick(rows=[_row(a), _row(b, firing=["p99"])])
        assert c._replicas[a].state == HALF_OPEN
        probe = c.select()
        assert probe.url == a
        assert c.select() is None           # trial slot taken
        c.report_success(probe)
        assert c._replicas[a].state == ADMITTED
        assert c.select().url == a

        # a failed probe re-ejects
        c.tick(rows=[_row(a), _row(b, firing=["p99"])])
        c.tick(rows=[_row(a), _row(b)])
        probe = c.select(exclude={a})
        assert probe.url == b and probe.state == HALF_OPEN
        c.report_failure(probe, "connect refused")
        assert c._replicas[b].state == EJECTED

        events = [e["event"] for e in telemetry.tracer.recent_events()]
        assert "router_failover" in events
        assert "router_readmit" in events
    finally:
        c.close()


def test_controller_consecutive_proxy_failures_eject():
    a, b = "http://a:1", "http://b:1"
    c = FleetController([a, b], interval=999.0, eject_failures=2)
    try:
        c.tick(rows=[_row(a), _row(b)])
        r = c._replicas[a]
        c.report_failure(r, "boom")
        assert r.state == ADMITTED          # one failure is noise
        c.report_failure(r, "boom")
        assert r.state == EJECTED           # threshold reached
        assert telemetry.get_registry().counter_total(
            "veles_router_ejections_total", reason="errors") == 1
        # stickiness falls back to the survivor, not the ejected one
        for i in range(8):
            assert c.select(sticky_key="session:%d" % i).url == b
    finally:
        c.close()


def test_controller_partial_scrape_ejects_and_keeps_gauges():
    a, b = "http://a:1", "http://b:1"
    c = FleetController([a, b], interval=999.0)
    try:
        c.tick(rows=[_row(a, queue=7.0), _row(b, queue=1.0)])
        # budget-truncated row: /healthz answered but the budget died
        # before /readyz — too slow to scrape is too slow to route to
        partial = {"url": a, "reachable": True, "ready": None,
                   "partial": True, "firing": [], "reasons": [],
                   "metrics": {}}
        c.tick(rows=[partial, _row(b, queue=1.0)])
        assert c._replicas[a].state == EJECTED
        # ...and the stale gauge is KEPT: zeroing it would make the
        # slowest replica the least-queue magnet on re-admission
        assert c._replicas[a].queue_rows == 7.0
        # a pre-health-plane process (ready None WITHOUT partial)
        # stays admitted — no /readyz surface is not a timeout
        bare = {"url": b, "reachable": True, "ready": None,
                "firing": [], "reasons": [], "metrics": {}}
        c.tick(rows=[_row(a), bare])
        assert c._replicas[b].state == ADMITTED
    finally:
        c.close()


def test_controller_drain_stops_new_requests():
    a, b = "http://a:1", "http://b:1"
    c = FleetController([a, b], interval=999.0)
    try:
        c.tick(rows=[_row(a), _row(b)])
        assert c.drain(a) == 0
        assert c._replicas[a].state == DRAINING
        for _ in range(6):
            assert c.select().url == b
        # drain survives healthy scrapes (it is an operator decision)
        c.tick(rows=[_row(a), _row(b)])
        assert c._replicas[a].state == DRAINING
        assert c.drain("http://nope:1") is None
    finally:
        c.close()


# -- unit: autoscaler ----------------------------------------------------


class FakeExecutor:
    actuates = True
    kind = "fake"

    def __init__(self, urls):
        self.urls = list(urls)
        self.launched = []
        self.stopped = []

    def launch(self):
        url = self.urls.pop(0) if self.urls else None
        if url:
            self.launched.append(url)
        return url

    def stop(self, url):
        self.stopped.append(url)

    def close(self):
        pass


def test_autoscaler_up_on_queue_down_via_drain():
    a, new = "http://a:1", "http://new:1"
    executor = FakeExecutor([new])
    scaler = Autoscaler(executor, min_replicas=1, max_replicas=2,
                        queue_high=10.0, queue_low=1.0,
                        sustain_ticks=2, cooldown_s=0.0)
    c = FleetController([a], interval=999.0, autoscaler=scaler)
    try:
        # sustained overload -> launch + admit the new replica
        c.tick(rows=[_row(a, queue=50.0)])
        assert executor.launched == []      # one tick is a blip
        c.tick(rows=[_row(a, queue=50.0)])
        # the launch runs off the control thread (a subprocess start
        # must not freeze the loop) — wait for it to land
        wait_until(lambda: new in c.targets(), what="launched target")
        assert executor.launched == [new]
        assert telemetry.get_registry().counter_total(
            "veles_router_scale_decisions_total", direction="up") == 1

        # sustained idle -> drain the launched replica, then stop it
        # once its inflight reaches zero
        idle = [_row(a, queue=0.0), _row(new, queue=0.0)]
        c.tick(rows=idle)
        c.tick(rows=idle)
        assert c._replicas[new].state == DRAINING
        c.tick(rows=idle)                   # drained -> stopped
        assert new not in c.targets()       # unrouted synchronously
        # the process stop itself runs off the control thread
        wait_until(lambda: executor.stopped == [new],
                   what="async executor stop")
        wait_until(lambda: "scale_down_complete" in [
            e["event"] for e in telemetry.tracer.recent_events()],
            what="scale_down_complete event")
        events = [e["event"] for e in telemetry.tracer.recent_events()]
        assert "scale_up" in events and "scale_down" in events
    finally:
        c.close()


def test_autoscaler_dry_run_records_without_actuating():
    a = "http://a:1"
    scaler = Autoscaler(DryRunExecutor(), min_replicas=1,
                        max_replicas=4, queue_high=10.0,
                        sustain_ticks=1, cooldown_s=0.0)
    c = FleetController([a], interval=999.0, autoscaler=scaler)
    try:
        c.tick(rows=[_row(a, firing=["p99_burn"], queue=0.0)])
        # firing SLO ejects the backend AND reads as scale-up signal
        assert scaler.decisions \
            and scaler.decisions[-1]["direction"] == "up" \
            and scaler.decisions[-1]["actuated"] is False
        assert c.targets() == [a]           # nothing launched
        doc = c.status_doc
        assert doc["autoscaler"]["last"]["direction"] == "up"
    finally:
        c.close()


# -- fleet scraper: parallel + time-bounded (satellite) ------------------


def test_parallel_scrape_bounded_by_wedged_target():
    healthy = StubReplica("fast")
    wedge = BrownoutProxy(("127.0.0.1", healthy.server.port))
    wedge.set_black_hole()                  # connects, never answers
    try:
        t0 = time.perf_counter()
        rows = fleet.scrape_targets(
            [healthy.url, wedge.url, healthy.url],
            timeout=0.5, total=0.5)
        wall = time.perf_counter() - t0
        # serial pre-ISSUE-13 behaviour: every surface of every
        # target queued behind the wedged one; now the wave is
        # bounded by ONE per-target budget
        assert wall < 3.0, wall
        assert rows[0]["ready"] is True
        assert rows[1]["reachable"] is False
        assert rows[2]["ready"] is True
    finally:
        wedge.close()
        healthy.close()


# -- live HTTP: the router in front of real sockets ----------------------


def _mk_router(stubs, **kw):
    kw.setdefault("interval", 0.15)
    kw.setdefault("scrape_timeout", 0.5)
    controller = FleetController([s if isinstance(s, str) else s.url
                                  for s in stubs], **kw)
    front = RouterFrontend(controller, port=0)
    return controller, front


def _wait_admitted(front, n, timeout=15.0):
    def check():
        doc = _get(front.url + "/router/status")[1]
        # ticks >= 1: the init doc lists configured backends as
        # admitted before any scrape confirmed them
        return doc if doc["ticks"] >= 1 and doc["admitted"] == n \
            else None
    return wait_until(check, timeout=timeout,
                      what="%d admitted backend(s)" % n)


def test_router_proxies_predict_with_trace_and_metrics():
    stub = StubReplica("m1")
    controller, front = _mk_router([stub])
    try:
        _wait_admitted(front, 1)
        trace = telemetry.TraceContext.new()
        code, doc, headers = _post(
            front.url + "/v1/predict", {"model": "m1", "inputs": [[1]]},
            headers={"traceparent": trace.to_traceparent()})
        assert code == 200 and doc["replica"] == "m1"
        # trace propagation: same trace_id reaches the replica on a
        # CHILD span, and the client gets its own context echoed
        upstream_tp = stub.last_headers.get("traceparent", "")
        assert trace.trace_id in upstream_tp
        assert upstream_tp != trace.to_traceparent()
        assert headers.get("traceparent") == trace.to_traceparent()
        assert stub.last_headers.get("x-forwarded-for")
        reg = telemetry.get_registry()
        # the outcome counter increments AFTER the reply is handed to
        # the reactor's write queue — the client can observe the
        # response a beat before the router thread settles accounting
        wait_until(
            lambda: reg.counter_total("veles_router_requests_total",
                                      replica=stub.url,
                                      outcome="ok") == 1,
            what="routed request counted")
        # routed latency histogram observed the request
        hist = fleet.parse_prometheus(
            reg.render_prometheus())
        assert fleet.metric_total(
            hist, "veles_router_request_seconds_count") >= 1
        # the router.proxy span carries the client's trace_id
        spans = telemetry.tracer.flight_spans()
        mine = [ev for _, ev in spans
                if ev.get("name") == "router.proxy"
                and ev.get("args", {}).get("trace_id")
                == trace.trace_id]
        assert mine and mine[-1]["args"]["replica"] == stub.url

        # velescli top sees a router row with its backends
        row = fleet.scrape_target(front.url, timeout=5.0)
        assert row["role"] == "router"
        assert [b["url"] for b in row["router"]["backends"]] \
            == [stub.url]
        rendered = fleet.render_snapshot(
            fleet.fleet_snapshot([front.url]))
        assert "router: 1/1 backend(s) admitted" in rendered
    finally:
        front.close()
        controller.close()
        stub.close()


def test_router_failover_keeps_inflight_stream_and_stickiness():
    a = StubReplica("a", tokens=15, token_interval=0.05)
    b = StubReplica("b", tokens=15, token_interval=0.05)
    controller, front = _mk_router([a, b])
    try:
        _wait_admitted(front, 2)

        def generate(session):
            req = urllib.request.Request(
                front.url + "/v1/generate",
                data=json.dumps({"model": "m",
                                 "prompt": [1]}).encode(),
                headers={"Content-Type": "application/json",
                         "x-veles-session": session})
            with urllib.request.urlopen(req, timeout=30) as resp:
                return [json.loads(line) for line in resp
                        if line.strip()]

        # discover where the session sticks (consistent hash)
        lines = generate("pin")
        assert lines[-1].get("done") is True
        sticky, other = (a, b) if a.generates else (b, a)
        # same session -> same replica, repeatedly
        for _ in range(3):
            assert generate("pin")[-1]["replica"] == sticky.model
        assert sticky.generates == 4 and other.generates == 0

        # start a long-lived stream on the sticky replica, then flip
        # the OTHER replica's readiness mid-stream
        req = urllib.request.Request(
            front.url + "/v1/generate",
            data=json.dumps({"model": "m", "prompt": [1]}).encode(),
            headers={"Content-Type": "application/json",
                     "x-veles-session": "pin"})
        resp = urllib.request.urlopen(req, timeout=30)
        other.ready = False
        wait_until(lambda: any(
            bk["state"] == EJECTED
            for bk in _get(front.url + "/router/status")[1]["backends"]
            if bk["url"] == other.url), what="readiness-flip ejection")
        # new work drains to the survivor...
        for _ in range(4):
            code, doc, _ = _post(front.url + "/v1/predict",
                                 {"model": "m", "inputs": [[1]]})
            assert code == 200 and doc["replica"] == sticky.model
        assert other.predicts == 0
        # ...while the in-flight stream is NOT re-routed: it finishes
        # on the replica it started on, token-complete
        lines = [json.loads(line) for line in resp if line.strip()]
        resp.close()
        assert lines[-1].get("done") is True
        assert lines[-1]["replica"] == sticky.model
        assert sum(1 for ln in lines if "token" in ln) == 15
        assert sticky.streams_completed >= 1

        # ejection is observable: counter + flight-recorder event
        assert telemetry.get_registry().counter_total(
            "veles_router_ejections_total", reason="not_ready") >= 1
        events = [e for e in telemetry.tracer.recent_events()
                  if e["event"] == "router_failover"]
        assert any(e.get("replica") == other.url for e in events)
    finally:
        front.close()
        controller.close()
        a.close()
        b.close()


def test_router_e2e_chaos_brownout_ejection_recovery_trace():
    """The acceptance scenario: 2 replicas behind ``velescli route``'s
    machinery, one browned out (BrownoutProxy latency + /readyz flip)
    -> ejected within 2 control ticks, zero routed requests land on
    it until recovery, one trace_id spans client -> router ->
    replica, half-open probe re-admits after restore."""
    a = StubReplica("a")
    b = StubReplica("b")
    proxy = BrownoutProxy(("127.0.0.1", a.server.port))
    controller, front = _mk_router(
        [proxy.url, b.url], interval=0.4, scrape_timeout=0.5)
    try:
        _wait_admitted(front, 2)
        # brown out A: every byte now crawls AND readiness flips —
        # the scrape sees a target that cannot answer in budget
        ticks0 = _get(front.url + "/router/status")[1]["ticks"]
        a.ready = False
        proxy.brownout(2.0)
        status = wait_until(
            lambda: next(
                (doc for doc in
                 [_get(front.url + "/router/status")[1]]
                 if any(bk["state"] == EJECTED
                        for bk in doc["backends"]
                        if bk["url"] == proxy.url)), None),
            what="brownout ejection")
        assert status["ticks"] - ticks0 <= 2, \
            "ejection took %d tick(s)" % (status["ticks"] - ticks0)

        # zero routed requests on the ejected replica, all on B —
        # with the client's trace_id stitched through the proxy
        a_before = a.predicts
        trace = telemetry.TraceContext.new()
        for _ in range(10):
            code, doc, _ = _post(
                front.url + "/v1/predict",
                {"model": "m", "inputs": [[1]]},
                headers={"traceparent": trace.to_traceparent()})
            assert code == 200 and doc["replica"] == "b"
        assert a.predicts == a_before
        assert b.last_headers.get("traceparent", "").startswith(
            "00-" + trace.trace_id)
        span_doc = _get(front.url + "/debug/trace")[1]
        mine = [ev for ev in span_doc["traceEvents"]
                if ev.get("name") == "router.proxy"
                and ev.get("args", {}).get("trace_id")
                == trace.trace_id]
        assert len(mine) == 10

        # recovery: restore the pipe + readiness; the next healthy
        # scrape half-opens A and ONE live request re-admits it
        proxy.restore()
        a.ready = True

        def readmitted():
            _post(front.url + "/v1/predict",
                  {"model": "m", "inputs": [[1]]})
            doc = _get(front.url + "/router/status")[1]
            return all(bk["state"] == ADMITTED
                       for bk in doc["backends"])
        wait_until(readmitted, interval=0.1,
                   what="half-open re-admission")
        assert a.predicts > a_before        # traffic reached A again
        events = [e["event"] for e in telemetry.tracer.recent_events()]
        assert "router_readmit" in events
    finally:
        front.close()
        controller.close()
        proxy.close()
        a.close()
        b.close()


def test_router_no_backend_503_and_drain_endpoint():
    stub = StubReplica("only")
    controller, front = _mk_router([stub])
    try:
        _wait_admitted(front, 1)
        # operator drain: new requests stop, the router flips its own
        # readiness (0 admitted backends)
        code, doc, _ = _post(front.url + "/router/drain",
                             {"url": stub.url})
        assert code == 200 and doc["draining"] == stub.url
        code, doc, headers = _post(front.url + "/v1/predict",
                                   {"model": "m", "inputs": [[1]]})
        assert code == 503 and "Retry-After" in headers
        assert stub.predicts == 0
        assert telemetry.get_registry().counter_total(
            "veles_router_requests_total", outcome="no_backend") == 1

        def router_not_ready():
            code, doc, _ = _get(front.url + "/readyz")
            return code == 503 and any(
                "backend" in r for r in doc["reasons"])
        wait_until(router_not_ready, what="router /readyz flip")
        code, doc, _ = _post(front.url + "/router/drain",
                             {"url": "http://unknown:1"})
        assert code == 404
    finally:
        front.close()
        controller.close()
        stub.close()


def test_host_port_parses_ipv6_literals():
    from veles.router import _host_port
    assert _host_port("http://127.0.0.1:9999") == ("127.0.0.1", 9999)
    assert _host_port("http://[::1]:8080") == ("::1", 8080)
    assert _host_port("http://replica") == ("replica", 80)


def test_route_cli_parser_and_dry_run_wiring():
    from veles.router import build_route_argparser
    args = build_route_argparser().parse_args(
        ["http://r1:8080", "http://r2:8080", "--port", "0",
         "--autoscale", "1:4", "--dry-run", "--queue-high", "16"])
    assert args.backends == ["http://r1:8080", "http://r2:8080"]
    assert args.autoscale == "1:4" and args.dry_run
    assert args.queue_high == 16.0
    with pytest.raises(SystemExit):
        build_route_argparser().parse_args([])   # backends required
