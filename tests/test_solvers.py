"""Adam/AdamW solver option on the GD family: trajectory equality
with optax.adamw as the external oracle, numpy↔XLA parity, gradient
accumulation gating, and LM convergence from config alone."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root
from veles.memory import Array
from veles.znicz_tpu.ops.attention import TransformerFFN
from veles.znicz_tpu.ops.moe import MoEFFN

from tests.test_conv_stack import build


ADAM = dict(solver="adam", learning_rate=0.01, gradient_moment=0.9,
            adam_beta2=0.999, adam_eps=1e-8, weights_decay=0.01)


def _steps_numpy(fwd, gd, n):
    for _ in range(n):
        fwd.numpy_run()
        gd.numpy_run()


def test_adam_matches_optax_adamw():
    """3 steps of the unit's adam == 3 steps of optax.adamw driven by
    the same per-step gradients (weight params; bias decays are 0 so
    bias follows the same rule with wd=0)."""
    import jax
    import jax.numpy as jnp
    import optax
    from tests.test_conv_stack import grad_oracle

    wf, feed, fwd, gd, x, err, comp = build(
        TransformerFFN, input_shape=(2, 4, 8), gd_kwargs=dict(ADAM),
        hidden=16)
    params0 = comp.gather_params()[fwd.name]
    # optax twin on the weight-family params (decayed) and bias family
    # (not decayed) — masks mirror the unit's weight/bias hyper split
    decay_mask = {k: k in ("weights", "weights2") for k in params0}
    opt = optax.adamw(learning_rate=0.01, b1=0.9, b2=0.999, eps=1e-8,
                      weight_decay=0.01, mask=decay_mask)
    ref = {k: jnp.asarray(v) for k, v in params0.items()}
    opt_state = opt.init(ref)
    for _ in range(3):
        # grads for the CURRENT unit params (shared trajectory as long
        # as both sides stay equal) via the jax.grad oracle
        cur = comp.gather_params()
        gp, _ = grad_oracle(comp, feed, fwd, cur, x, err)
        grads = {k: jnp.asarray(v) for k, v in gp[fwd.name].items()}
        upd, opt_state = opt.update(grads, opt_state, ref)
        ref = optax.apply_updates(ref, upd)
        _steps_numpy(fwd, gd, 1)
    for k in params0:
        got = getattr(fwd, k).map_read().mem
        want = numpy.asarray(ref[k])
        assert numpy.allclose(got, want, atol=2e-5), \
            (k, numpy.abs(got - want).max())


@pytest.mark.parametrize("cls,kwargs", [
    (TransformerFFN, dict(hidden=16)),
    (MoEFFN, dict(experts=2, hidden=8)),
], ids=["ffn", "moe"])
def test_adam_numpy_xla_parity(cls, kwargs):
    """Two adam steps: traced path == numpy oracle on every param and
    on the adam state (first/second moments)."""
    import jax
    from veles.accelerated_units import FlowContext

    wf, feed, fwd, gd, x, err, comp = build(
        cls, input_shape=(2, 4, 8), gd_kwargs=dict(ADAM), **kwargs)
    params0 = comp.gather_params()
    state0 = comp.gather_state()

    def fn(p, s, xv, ev):
        ctx = FlowContext(comp, dict(p), dict(s),
                          {gd.name: gd.hyperparams()},
                          jax.random.PRNGKey(7), True)
        ctx.set(feed, "minibatch_data", xv)
        fwd.xla_run(ctx)
        ctx.set(gd, "err_output", ev)
        gd.xla_run(ctx)
        return ctx.params, ctx.state

    step = jax.jit(fn)
    p, s = step(params0, state0, x, err)
    p, s = step(p, s, x, err)
    _steps_numpy(fwd, gd, 2)
    for k in fwd.PARAMS:
        got = numpy.asarray(p[fwd.name][k])
        want = getattr(fwd, k).map_read().mem
        assert numpy.allclose(got, want, atol=5e-5), k
    # second moments really advanced and match
    sq = s[gd.name].get("sq_weights")
    assert sq is not None and float(numpy.abs(sq).max()) > 0
    assert numpy.allclose(numpy.asarray(sq),
                          gd.sq_weights.map_read().mem, atol=1e-6)


def test_adam_accumulation_gates_all_state():
    """accumulate_gradient=2: nothing (params, m, v) moves on the odd
    step; everything applies on the even step."""
    wf, feed, fwd, gd, x, err, comp = build(
        TransformerFFN, input_shape=(2, 4, 8),
        gd_kwargs=dict(ADAM, accumulate_gradient=2), hidden=16)
    p0 = {k: numpy.array(getattr(fwd, k).mem) for k in fwd.PARAMS}
    gd.numpy_run()
    for k in fwd.PARAMS:
        assert numpy.allclose(getattr(fwd, k).mem, p0[k]), k
    assert not gd.sq_weights.map_read().mem.any()
    fwd.numpy_run()
    gd.numpy_run()
    assert not numpy.allclose(fwd.weights.mem, p0["weights"])
    assert gd.sq_weights.map_read().mem.any()


def test_accumulation_sums_both_gradients():
    """The applied update must use the SUM of the accumulated
    gradients, not just the final minibatch's (momentum solver,
    lr=1, moment=0: w1 - w0 == -(g1 + g2) exactly)."""
    import jax
    from tests.test_conv_stack import grad_oracle

    wf, feed, fwd, gd, x, err, comp = build(
        TransformerFFN, input_shape=(2, 4, 8),
        gd_kwargs=dict(learning_rate=1.0, gradient_moment=0.0,
                       weights_decay=0.0, accumulate_gradient=2),
        hidden=16)
    w0 = numpy.array(fwd.weights.mem)
    params0 = comp.gather_params()
    gp1, _ = grad_oracle(comp, feed, fwd, params0, x, err)
    g1 = numpy.asarray(gp1[fwd.name]["weights"])
    gd.numpy_run()                     # step 1: accumulate only
    err2 = err * 0.5                   # different gradient on step 2
    gd.err_output = Array(err2)
    fwd.numpy_run()
    gp2, _ = grad_oracle(comp, feed, fwd, params0, x, err2)
    g2 = numpy.asarray(gp2[fwd.name]["weights"])
    gd.numpy_run()                     # step 2: apply the sum
    delta = fwd.weights.map_read().mem - w0
    assert numpy.allclose(delta, -(g1 + g2), atol=1e-5), \
        numpy.abs(delta + g1 + g2).max()


def test_lm_trains_with_adam_from_config():
    """solver=adam in the layer '<-' dicts trains the LM (XLA path)
    and beats the first-epoch error."""
    prng.seed_all(808)
    from veles.znicz_tpu.models import transformer_lm
    saved_loader = root.lm.loader.to_dict()
    saved_model = root.lm.model.to_dict()
    saved_train = root.lm.train.to_dict()
    saved_epochs = root.lm.decision.get("max_epochs")
    root.lm.loader.update({"minibatch_size": 32, "n_train": 512,
                           "n_valid": 128, "seq_len": 16, "vocab": 8,
                           "max_period": 4})
    root.lm.model.update({"dim": 32, "heads": 2, "layers": 1,
                          "ffn_hidden": 64, "moe_experts": 0,
                          "attn_block": None, "attn_impl": None,
                          "stacked": False})
    root.lm.train.update({"solver": "adam", "learning_rate": 0.005,
                          "gradient_moment": 0.9,
                          "weights_decay": 0.0})
    root.lm.decision.max_epochs = 6
    root.lm.parallel.update({"seq": 1, "model": 1, "data": 1,
                             "expert": 1, "pipe": 1})
    try:
        wf = transformer_lm.create_workflow(name="AdamLM")
        wf.initialize(device="xla")
        wf.run()
    finally:
        root.lm.loader.update(saved_loader)
        root.lm.model.update(saved_model)
        # Config has no key deletion: neutralize the added solver
        # keys explicitly, then restore the original values
        root.lm.train.update({"solver": "momentum"})
        root.lm.train.update(saved_train)
        root.lm.decision.max_epochs = saved_epochs
    hist = [h["validation"]["metric"] for h in wf.decision.history]
    assert hist[-1] < hist[0], hist


def test_warmup_cosine_policy():
    """warmup_cosine: linear ramp (t+1)/warmup — NONZERO at t=0 so the
    first optimizer step isn't a no-op — reaching base at t=warmup-1,
    cosine decay to min_ratio*base at t=total, flat after; numpy ==
    traced values."""
    import jax
    import jax.numpy as jnp
    from veles.znicz_tpu.lr_adjust import make_policy

    pol = make_policy({"name": "warmup_cosine", "warmup": 10,
                       "total": 110, "min_ratio": 0.1})
    base = 0.4
    assert abs(pol(numpy, base, 0) - base * 0.1) < 1e-7
    assert pol(numpy, base, 0) > 0.0
    assert abs(pol(numpy, base, 5) - base * 0.6) < 1e-6
    assert abs(pol(numpy, base, 9) - base) < 1e-6
    assert abs(pol(numpy, base, 10) - base) < 1e-6
    mid = pol(numpy, base, 60)           # halfway through the decay
    assert abs(mid - base * 0.55) < 1e-6  # 0.1 + 0.9*0.5
    assert abs(pol(numpy, base, 110) - base * 0.1) < 1e-6
    assert abs(pol(numpy, base, 500) - base * 0.1) < 1e-6
    for t in (0, 5, 10, 60, 110, 500):
        traced = jax.jit(lambda tt: pol(jnp, jnp.float32(base),
                                        tt))(jnp.int32(t))
        assert abs(float(traced) - pol(numpy, base, t)) < 1e-6, t
