"""Image-loader family + AlexNet sample tests (SURVEY.md §2.3 "Image
loaders", §2.8 ImageNet row): directory ingestion, label-from-path,
augmentation geometry, and the flagship conv stack training end-to-end
through the streaming pipeline."""

import os

import numpy
import pytest

import veles.prng as prng
from veles.config import root


@pytest.fixture(scope="module")
def image_tree(tmp_path_factory):
    """3 class dirs × 12 PNGs of distinct solid colors."""
    from PIL import Image
    base = tmp_path_factory.mktemp("imgs")
    colors = {"apple": (200, 30, 30), "pear": (30, 200, 30),
              "plum": (30, 30, 200)}
    gen = numpy.random.Generator(numpy.random.PCG64(7))
    for cls, color in colors.items():
        d = base / cls
        d.mkdir()
        for i in range(12):
            arr = numpy.clip(
                numpy.asarray(color)[None, None]
                + gen.normal(0, 12, (40, 48, 3)), 0, 255
            ).astype(numpy.uint8)
            Image.fromarray(arr).save(d / ("img%02d.png" % i))
    return str(base)


def _make_loader(image_tree, **kw):
    from veles.loader.image import AutoLabelFileImageLoader
    from veles.workflow import Workflow

    prng.seed_all(5)
    wf = Workflow(None, name="ImgWF")
    kw.setdefault("scale", (32, 32))
    kw.setdefault("crop", (28, 28))
    kw.setdefault("mirror", "random")
    kw.setdefault("minibatch_size", 8)
    ld = AutoLabelFileImageLoader(wf, base_dir=image_tree,
                                  name="loader", **kw)
    ld.initialize()
    return ld


def test_auto_label_split_and_classes(image_tree):
    ld = _make_loader(image_tree)
    # 36 images, valid_ratio 0.1 → stride 10: ceil split per class dir
    assert sum(ld.class_lengths) == 36
    assert ld.class_lengths[1] > 0 and ld.class_lengths[2] > 0
    assert ld.n_classes == 3
    labels = {ld.label_of(i) for i in range(sum(ld.class_lengths))}
    assert labels == {0, 1, 2}


def test_decode_augment_shapes(image_tree):
    ld = _make_loader(image_tree)
    out = ld.materialize_samples(numpy.arange(5))
    assert out["data"].shape == (5, 28, 28, 3)
    assert out["data"].dtype == numpy.uint8
    assert out["labels"].shape == (5,)


def test_eval_crop_deterministic(image_tree):
    """Eval phase: center crop, no mirror — bitwise repeatable."""
    ld = _make_loader(image_tree)
    ld.train_phase << False
    a = ld.materialize_samples(numpy.arange(4))["data"]
    b = ld.materialize_samples(numpy.arange(4))["data"]
    assert numpy.array_equal(a, b)


def test_label_colors_learnable(image_tree):
    """The solid-color classes must be learnable through the full
    streaming pipeline (decode → augment → ship → conv stack)."""
    from veles.znicz_tpu.standard_workflow import StandardWorkflow

    prng.seed_all(11)
    from veles.loader.image import AutoLabelFileImageLoader
    layers = [
        {"type": "conv_relu",
         "->": {"n_kernels": 8, "kx": 5, "ky": 5, "sliding": 2},
         "<-": {"learning_rate": 0.02, "gradient_moment": 0.5}},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "softmax", "->": {"output_sample_shape": 3},
         "<-": {"learning_rate": 0.02, "gradient_moment": 0.5}},
    ]
    wf = StandardWorkflow(
        None, name="ImgTrain", layers=layers,
        loader_factory=lambda w: AutoLabelFileImageLoader(
            w, base_dir=image_tree, name="loader", scale=(32, 32),
            crop=(28, 28), mirror="random", minibatch_size=8),
        decision_config={"max_epochs": 6, "fail_iterations": 50})
    wf.initialize(device="cpu")
    assert wf.xla_step.stream_mode
    wf.run()
    hist = [h["validation"]["metric"] for h in wf.decision.history]
    assert hist[-1] < 0.5, hist   # 3 classes, random = 0.67


def test_window_materialization_uses_class_phase(image_tree):
    """The fused dispatch builds every window of an epoch while the
    loader's live train_phase still points at the FIRST class served —
    materialize_window must derive augmentation from the class being
    built, not the serving gate (regression: train windows silently
    got eval augmentation)."""
    from veles.loader.base import CLASS_TRAIN, CLASS_VALID
    ld = _make_loader(image_tree)
    ld.train_phase << False          # epoch starts serving VALID
    rows = numpy.arange(8).reshape(2, 4)
    a = ld.materialize_window(CLASS_TRAIN, rows)["data"]
    b = ld.materialize_window(CLASS_TRAIN, rows)["data"]
    # train windows get random crops/mirrors: same epoch+indices give
    # the SAME (seeded) augmentation, but it must differ from eval's
    # deterministic center crop
    numpy.testing.assert_array_equal(a, b)
    ev = ld.materialize_window(CLASS_VALID, rows)["data"]
    assert not numpy.array_equal(a, ev), \
        "train window materialized with eval augmentation"


def test_synthetic_bank_eval_not_mirrored():
    """Eval minibatches must see the true pixels — mirroring is a
    TRAIN-only augmentation in both the oracle and device formulas."""
    from veles.workflow import Workflow
    from veles.znicz_tpu.models.imagenet import SyntheticImageLoader
    prng.seed_all(77)
    wf = Workflow(None, name="BankWF")
    ld = SyntheticImageLoader(wf, name="loader", n_classes=4,
                              n_train=24, n_valid=8, scale=(40, 40),
                              crop=(32, 32), minibatch_size=8)
    ld.initialize()
    bank = ld.original_data.mem
    y, x = ld._crop_origin()
    expect = ((bank[:8, y:y + 32, x:x + 32, :].astype(numpy.float32)
               / 255.0 - 0.5) / 0.5)
    got = ld._augment(numpy, bank[:8], train=False)
    numpy.testing.assert_array_equal(got, expect)
    trained = ld._augment(numpy, bank[:8], train=True)
    assert not numpy.array_equal(trained, expect)  # mirror applied
    # host fill in eval phase serves the un-mirrored crop
    ld.train_phase << False
    ld.minibatch_indices.mem[...] = numpy.arange(8)
    ld.minibatch_size = 8
    ld.fill_minibatch()
    numpy.testing.assert_array_equal(
        ld.minibatch_data.mem, expect)


def test_alexnet_sample_trains_scaled_down():
    """The AlexNet sample (full layer stack, reduced geometry) trains
    through the synthetic DEVICE-RESIDENT bank loader (scan fast path
    with the on-device crop/mirror/normalize transform); the streaming
    path stays covered by the file-loader test above."""
    from veles.znicz_tpu.models import imagenet

    prng.seed_all(13)
    saved = imagenet.root.imagenet.loader.to_dict()
    root.imagenet.loader.update({
        "minibatch_size": 8, "n_train": 48, "n_valid": 16,
        "n_classes": 4, "scale": (75, 75), "crop": (67, 67)})
    root.imagenet.decision.max_epochs = 3
    try:
        wf = imagenet.create_workflow(name="AlexTiny")
        wf.initialize(device="cpu")
        assert wf.xla_step.scan_mode
        wf.run()
    finally:
        root.imagenet.loader.update(saved)
    assert len(wf.decision.history) == 3
    # dropout/LRN/pool geometry all exercised; training must not blow up
    losses = [h["train"]["loss"] for h in wf.decision.history]
    assert numpy.isfinite(losses).all()
    assert losses[-1] < losses[0] * 1.5


def test_file_image_loader_explicit_labels(image_tree):
    from veles.loader.image import FileImageLoader
    from veles.workflow import Workflow

    prng.seed_all(3)
    wf = Workflow(None, name="FileWF")
    paths = []
    for cls in sorted(os.listdir(image_tree)):
        d = os.path.join(image_tree, cls)
        paths += [os.path.join(d, f) for f in sorted(os.listdir(d))[:3]]
    ld = FileImageLoader(
        wf, name="loader", train_paths=paths[3:],
        valid_paths=paths[:3],
        train_labels=list(range(len(paths) - 3)),
        valid_labels=[0, 1, 2],
        scale=(16, 16), minibatch_size=4)
    ld.initialize()
    assert ld.class_lengths == [0, 3, len(paths) - 3]
    out = ld.materialize_samples(numpy.asarray([0, 1, 2]))
    assert list(out["labels"]) == [0, 1, 2]
    assert out["data"].shape == (3, 16, 16, 3)
