"""Streaming-mode tests: the host→device windowed path must reproduce
the device-resident scan path exactly (same data, same shuffles, same
math — only the transport differs), including under DP sharding and
with device-side batch transforms (uint8 shipping)."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root


@pytest.fixture(autouse=True, scope="module")
def _restore_mnist_config():
    """These tests shrink root.mnist.loader; other modules rely on the
    sample defaults, so restore after the module. Module-scoped and
    autouse so it wraps (runs before) the module-scoped build
    fixtures that do the mutation."""
    import veles.znicz_tpu.models.mnist  # noqa: ensure defaults exist
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    yield
    root.mnist.loader.update(
        {k: v for k, v in saved.items() if v is not None})


def _mnist_arrays():
    from veles.znicz_tpu.models import datasets
    tx, ty, vx, vy = datasets.load_mnist(n_train=400, n_valid=100)
    tx = tx.reshape(len(tx), -1)
    vx = vx.reshape(len(vx), -1)
    data = numpy.concatenate([vx, tx]).astype(numpy.float32)
    labels = numpy.concatenate([vy, ty])
    return data, labels, [0, len(vx), len(tx)]


def _build(loader_kind, name, max_epochs=3):
    from veles.loader.fullbatch import FullBatchLoader
    from veles.loader.stream import ArrayStreamLoader
    from veles.znicz_tpu.models import mnist  # noqa: populates root.mnist
    from veles.znicz_tpu.standard_workflow import StandardWorkflow

    prng.seed_all(2468)
    root.mnist.loader.update({"n_train": 400, "n_valid": 100})
    data, labels, class_lengths = _mnist_arrays()

    def factory(wf):
        if loader_kind == "full":
            # identical arrays injected directly — the ONLY difference
            # vs the stream build is the transport
            ld = FullBatchLoader(wf, name="loader", minibatch_size=32)
            ld.original_data.mem = data.copy()
            ld.original_labels.mem = labels.copy()
            ld.class_lengths = list(class_lengths)
            return ld
        return ArrayStreamLoader(
            wf, name="loader", minibatch_size=32, data=data,
            labels=labels, class_lengths=class_lengths)

    wf = StandardWorkflow(
        None, name=name, layers=root.mnist.layers,
        loader_factory=factory,
        decision_config={"max_epochs": max_epochs,
                         "fail_iterations": 50})
    wf.initialize(device="cpu")
    return wf


def test_stream_mode_selected():
    wf = _build("stream", "StreamSel")
    assert wf.xla_step.stream_mode and not wf.xla_step.scan_mode
    wf2 = _build("full", "FullSel")
    assert wf2.xla_step.scan_mode and not wf2.xla_step.stream_mode


def test_stream_matches_fullbatch():
    """Same data served via streaming windows == device-resident scan
    (both backends consume identical minibatches in identical order)."""
    wf_full = _build("full", "FullRef")
    wf_full.run()
    wf_str = _build("stream", "StreamRun")
    wf_str.run()
    h_full = wf_full.decision.history
    h_str = wf_str.decision.history
    assert len(h_full) == len(h_str) == 3
    for a, b in zip(h_full, h_str):
        assert a["validation"]["metric"] == b["validation"]["metric"]
        assert abs(a["train"]["loss"] - b["train"]["loss"]) < 1e-5


def test_stream_small_windows_match():
    """Window boundaries must not affect results."""
    wf_a = _build("stream", "StreamW2")
    wf_a.xla_step.max_window_minibatches = 2
    wf_a.run()
    wf_b = _build("stream", "StreamW64")
    wf_b.run()
    for a, b in zip(wf_a.decision.history, wf_b.decision.history):
        assert a["validation"]["metric"] == b["validation"]["metric"]
        assert abs(a["train"]["loss"] - b["train"]["loss"]) < 1e-5


def test_stream_uint8_transform():
    """Ship uint8, normalize on device via xla_batch_transform."""
    from veles.loader.stream import ArrayStreamLoader
    from veles.znicz_tpu.models import mnist  # noqa: populates root.mnist
    from veles.znicz_tpu.standard_workflow import StandardWorkflow

    prng.seed_all(99)
    data, labels, class_lengths = _mnist_arrays()
    data_u8 = numpy.clip(data * 255.0, 0, 255).astype(numpy.uint8)

    class U8Loader(ArrayStreamLoader):
        def xla_batch_transform(self, name, tensor, train=False):
            if name == "data":
                import jax.numpy as jnp
                return tensor.astype(jnp.float32) / 255.0
            return tensor

        def fill_minibatch(self):      # host path parity
            super().fill_minibatch()
            self.minibatch_data.mem[...] = \
                self.minibatch_data.mem.astype(numpy.float32) / 255.0

    wf = StandardWorkflow(
        None, name="StreamU8", layers=root.mnist.layers,
        loader_factory=lambda w: U8Loader(
            w, name="loader", minibatch_size=32, data=data_u8,
            labels=labels, class_lengths=class_lengths),
        decision_config={"max_epochs": 3, "fail_iterations": 50})
    wf.initialize(device="cpu")
    # serve dtype is uint8: the host→device link carries bytes
    assert wf.loader.minibatch_data.mem.dtype == numpy.uint8
    wf.run()
    hist = [h["validation"]["metric"] for h in wf.decision.history]
    assert hist[-1] <= hist[0]


def test_stream_data_parallel():
    """Streaming + DP sharding on the 8-device mesh, non-divisible
    minibatch (32 % 8 == 0 is boring; use 12)."""
    from veles.loader.stream import ArrayStreamLoader
    from veles.znicz_tpu import parallel
    from veles.znicz_tpu.models import mnist  # noqa: populates root.mnist
    from veles.znicz_tpu.standard_workflow import StandardWorkflow

    prng.seed_all(31)
    data, labels, class_lengths = _mnist_arrays()
    wf = StandardWorkflow(
        None, name="StreamDP", layers=root.mnist.layers,
        loader_factory=lambda w: ArrayStreamLoader(
            w, name="loader", minibatch_size=12, data=data,
            labels=labels, class_lengths=class_lengths),
        decision_config={"max_epochs": 2, "fail_iterations": 50})
    wf.initialize(device="cpu")
    parallel.setup_data_parallel(wf, parallel.make_mesh({"data": 8}))
    assert wf.xla_step.stream_mode
    wf.run()
    assert len(wf.decision.history) == 2
