"""Test harness setup.

Force jax onto a virtual 8-device CPU platform BEFORE jax is imported
anywhere, per SURVEY.md §4 "TPU build translation": multi-device logic is
tested with ``--xla_force_host_platform_device_count=8`` (the honest
analogue of the reference's fake-transport distributed tests), and the
real-TPU path is exercised by ``bench.py`` / the driver instead.
"""

import os
import sys

# Force, don't setdefault: the outer environment pins JAX_PLATFORMS to
# the real TPU tunnel (and a sitecustomize imports jax at interpreter
# startup), but tests must run on the virtual CPU mesh. Overriding the
# env var alone is not enough once jax is already imported, so also
# flip the live jax config before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache: the CI box spends a large share of the
# tier-1 budget recompiling the same programs every run (measured 16s
# -> 9s on tests/test_flash_attention.py alone). Keyed by program
# fingerprint, so it can never serve a stale computation. REPO-local
# (gitignored), not /tmp: the sandbox gives each process a private
# /tmp, which would silently discard the cache between runs.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))),
                       ".jax_compile_cache")))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return numpy.random.Generator(numpy.random.PCG64(1234))


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    """Every test runs under a FRESH scoped telemetry registry
    (veles/telemetry.py): instruments created by one test can never
    leak counts into another or into tier-1 flakiness. LazyChild
    handles on long-lived units re-resolve automatically when the
    registry generation changes. The span tracer is reset too, in
    case a test enabled it and failed before stopping."""
    from veles import telemetry
    with telemetry.scoped():
        yield
    telemetry.tracer.stop()
    telemetry.tracer.clear()


@pytest.fixture(autouse=True)
def _model_health_isolation():
    """Each test gets a fresh model-health monitor
    (veles/model_health.py): layer stats, the loss EWMA and the
    divergence verdict one test's training run produces can never
    leak into another's /debug/model or SLO evaluation."""
    from veles import model_health
    with model_health.scoped():
        yield


@pytest.fixture(autouse=True)
def _tenant_table_isolation():
    """The per-tenant QoS table (veles/serving/tenants.py) is
    process-global by design; a test that installs one must never
    leave quotas/weights behind for the next test's frontends."""
    yield
    from veles.serving import tenants
    tenants.set_table(None)


@pytest.fixture(autouse=True)
def _health_isolation():
    """Each test gets a fresh health monitor (veles/health.py): the
    readiness checks and SLO alert state one test registers (web
    status, serving frontends, masters) can never leak into another.
    The monitor is closed on exit so no sampler thread outlives its
    test."""
    from veles import health
    with health.scoped():
        yield
