"""Test harness setup.

Force jax onto a virtual 8-device CPU platform BEFORE jax is imported
anywhere, per SURVEY.md §4 "TPU build translation": multi-device logic is
tested with ``--xla_force_host_platform_device_count=8`` (the honest
analogue of the reference's fake-transport distributed tests), and the
real-TPU path is exercised by ``bench.py`` / the driver instead.
"""

import os
import sys

# Force, don't setdefault: the outer environment pins JAX_PLATFORMS to
# the real TPU tunnel (and a sitecustomize imports jax at interpreter
# startup), but tests must run on the virtual CPU mesh. Overriding the
# env var alone is not enough once jax is already imported, so also
# flip the live jax config before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return numpy.random.Generator(numpy.random.PCG64(1234))
