"""Kohonen SOM + RBM functional tests (BASELINE config #4; SURVEY.md §7
stage 7 — the custom-update, non-backprop unit path)."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root


def run_kohonen(backend):
    prng.seed_all(77)
    from veles.znicz_tpu.models import kohonen
    root.kohonen.decision.max_epochs = 10
    root.kohonen.loader.n_samples = 600
    wf = kohonen.create_workflow(name="Koh_%s" % backend)
    wf.initialize(device=backend)
    wf.run()
    return wf


def quantization_error(wf):
    loader = wf.loader
    x = loader.original_data.mem
    w = wf.forwards[0].weights.map_read().mem
    d = ((x[:, None, :] - w[None, :, :]) ** 2).sum(axis=-1)
    return float(numpy.sqrt(d.min(axis=1)).mean())


def test_kohonen_numpy_converges():
    wf = run_kohonen("numpy")
    qe = quantization_error(wf)
    # untrained map: weights are tiny uniform noise around 0 while the
    # data lives in [-1, 1]² — mean distance ~0.9
    assert qe < 0.3, qe
    deltas = [h["train"]["metric"] for h in wf.decision.history]
    assert deltas[-1] < deltas[0]


def test_kohonen_xla_matches():
    wf = run_kohonen("cpu")
    assert wf.xla_step is not None and wf.xla_step.scan_mode
    qe = quantization_error(wf)
    assert qe < 0.3, qe
    wf2 = run_kohonen("numpy")
    assert abs(qe - quantization_error(wf2)) < 0.1


def run_rbm(backend):
    prng.seed_all(88)
    from veles.znicz_tpu.models import mnist_rbm
    root.mnist_rbm.loader.n_train = 800
    root.mnist_rbm.loader.n_valid = 200
    root.mnist_rbm.decision.max_epochs = 6
    wf = mnist_rbm.create_workflow(name="RBM_%s" % backend)
    wf.initialize(device=backend)
    wf.run()
    return wf


def test_rbm_numpy_reconstruction_improves():
    wf = run_rbm("numpy")
    hist = [h["validation"]["metric"] for h in wf.decision.history]
    assert hist[-1] < hist[0] * 0.87, hist


def test_rbm_xla_matches():
    wf = run_rbm("cpu")
    hist = [h["validation"]["metric"] for h in wf.decision.history]
    assert hist[-1] < hist[0] * 0.87, hist
    wf2 = run_rbm("numpy")
    hist2 = [h["validation"]["metric"] for h in wf2.decision.history]
    # stochastic binarization differs per backend; trajectories should
    # still land in the same neighbourhood
    assert abs(hist[-1] - hist2[-1]) / max(hist2[-1], 1e-9) < 0.35, \
        (hist, hist2)
