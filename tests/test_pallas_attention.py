"""Pallas flash-attention kernels: exact parity with the scan-flash
and dense formulations (interpret mode on CPU; the same kernels run
natively on TPU), and the attention unit's pallas path against the
dense numpy oracle."""

import numpy
import pytest

import veles.prng as prng
from veles.memory import Array
from veles.znicz_tpu.ops.attention import MultiHeadAttention
from veles.znicz_tpu.parallel import flash, pallas_attention as PA

from tests.test_conv_stack import build, xla_forward, xla_backward


CASES = [
    dict(causal=True, s=64, block=32),
    dict(causal=False, s=64, block=32),
    dict(causal=True, s=128, block=64),
    dict(causal=True, s=64, block=64),   # single block
]


def _qkv(s, b=2, h=2, dh=8, seed=909):
    prng.seed_all(seed)
    gen = prng.get("pa")
    shape = (b, h, s, dh)
    return tuple(gen.normal(0, 1.0, shape).astype(numpy.float32)
                 for _ in range(3))


@pytest.mark.parametrize("case", CASES, ids=lambda c: str(c))
def test_pallas_fwd_matches_scan_flash(case):
    q, k, v = _qkv(case["s"])
    out_ref, lse_ref = flash.blocked_attention_fwd(
        q, k, v, causal=case["causal"], block=case["block"])
    out, lse = PA.flash_attention_fwd(
        q, k, v, causal=case["causal"], block_q=case["block"],
        block_k=case["block"], interpret=True)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(out_ref),
                          atol=2e-5), \
        numpy.abs(numpy.asarray(out) - numpy.asarray(out_ref)).max()
    assert numpy.allclose(numpy.asarray(lse), numpy.asarray(lse_ref),
                          atol=2e-5)


@pytest.mark.parametrize("case", CASES, ids=lambda c: str(c))
def test_pallas_bwd_matches_scan_flash(case):
    q, k, v = _qkv(case["s"])
    prng.seed_all(910)
    dout = prng.get("pa2").normal(0, 1.0, q.shape).astype(
        numpy.float32)
    out, lse = flash.blocked_attention_fwd(
        q, k, v, causal=case["causal"], block=case["block"])
    refs = flash.blocked_attention_bwd(
        q, k, v, out, lse, dout, causal=case["causal"],
        block=case["block"])
    got = PA.flash_attention_bwd(
        q, k, v, out, lse, dout, causal=case["causal"],
        block_q=case["block"], block_k=case["block"], interpret=True)
    for name, r, g in zip(("dq", "dk", "dv"), refs, got):
        assert numpy.allclose(numpy.asarray(g), numpy.asarray(r),
                              atol=2e-4), \
            (name,
             numpy.abs(numpy.asarray(g) - numpy.asarray(r)).max())


@pytest.mark.parametrize("case", CASES, ids=lambda c: str(c))
def test_pallas_bwd_fused_matches_two_kernel(case):
    """The single-pass dk/dv/dq kernel (dq accumulated in a revisited
    output ref across the sequential k-block grid — round 5, measured
    +38% on the backward at S=8k) must agree leaf-for-leaf with the
    retained two-kernel formulation."""
    q, k, v = _qkv(case["s"])
    prng.seed_all(911)
    dout = prng.get("pa3").normal(0, 1.0, q.shape).astype(
        numpy.float32)
    out, lse = PA.flash_attention_fwd(
        q, k, v, causal=case["causal"], block_q=case["block"],
        block_k=case["block"], interpret=True)
    two = PA.flash_attention_bwd(
        q, k, v, out, lse, dout, causal=case["causal"],
        block_q=case["block"], block_k=case["block"], interpret=True,
        fused=False)
    one = PA.flash_attention_bwd(
        q, k, v, out, lse, dout, causal=case["causal"],
        block_q=case["block"], block_k=case["block"], interpret=True,
        fused=True)
    for name, a, b in zip(("dq", "dk", "dv"), two, one):
        assert numpy.allclose(numpy.asarray(a), numpy.asarray(b),
                              atol=2e-5), \
            (name,
             numpy.abs(numpy.asarray(a) - numpy.asarray(b)).max())


@pytest.mark.parametrize("bq,bk", [(32, 16), (16, 32)],
                         ids=["bq>bk", "bq<bk"])
@pytest.mark.parametrize("causal", [True, False],
                         ids=["causal", "full"])
def test_pallas_unequal_blocks(bq, bk, causal):
    """UNEQUAL block_q/block_k exercise the hand-derived diagonal
    split boundaries (round 5: the floor/ceil clear points differ
    from the trivial qi/ki±1 values only here) — fwd vs the scan
    flash, and BOTH backward forms vs the scan backward."""
    s = 64
    q, k, v = _qkv(s)
    prng.seed_all(912)
    dout = prng.get("pa4").normal(0, 1.0, q.shape).astype(
        numpy.float32)
    out_ref, lse_ref = flash.blocked_attention_fwd(
        q, k, v, causal=causal, block=16)
    out, lse = PA.flash_attention_fwd(
        q, k, v, causal=causal, block_q=bq, block_k=bk,
        interpret=True)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(out_ref),
                          atol=2e-5)
    assert numpy.allclose(numpy.asarray(lse), numpy.asarray(lse_ref),
                          atol=2e-5)
    refs = flash.blocked_attention_bwd(
        q, k, v, out_ref, lse_ref, dout, causal=causal, block=16)
    for fused in (False, True):
        got = PA.flash_attention_bwd(
            q, k, v, out, lse, dout, causal=causal, block_q=bq,
            block_k=bk, interpret=True, fused=fused)
        for name, r, g in zip(("dq", "dk", "dv"), refs, got):
            assert numpy.allclose(numpy.asarray(g), numpy.asarray(r),
                                  atol=2e-4), \
                (fused, name,
                 numpy.abs(numpy.asarray(g) - numpy.asarray(r)).max())


def test_attention_unit_pallas_path():
    """The unit with attn_impl='pallas': traced forward and backward
    must match the dense numpy oracle (different formulation, same
    math)."""
    wf, feed, fwd, gd, x, err, comp = build(
        MultiHeadAttention, input_shape=(2, 32, 16), gd_kwargs={},
        heads=2, attn_impl="pallas", attn_block_size=16)
    golden = numpy.array(fwd.output.mem)          # dense numpy oracle
    params0 = comp.gather_params()
    state0 = comp.gather_state()
    y = xla_forward(comp, feed, fwd, params0, x)
    assert numpy.allclose(numpy.asarray(y), golden, atol=3e-5)
    gd.numpy_run()                                # dense oracle bwd
    ei_np = numpy.array(gd.err_input.mem)
    ei_x, params1 = xla_backward(comp, feed, fwd, gd, params0, state0,
                                 x, err)
    assert numpy.allclose(ei_np, numpy.asarray(ei_x), atol=3e-4), \
        numpy.abs(ei_np - numpy.asarray(ei_x)).max()
    for pname in fwd.PARAMS:
        w1_np = getattr(fwd, pname).map_read().mem
        w1_x = numpy.asarray(params1[fwd.name][pname])
        assert numpy.allclose(w1_np, w1_x, atol=5e-4), pname


def test_lm_trains_with_pallas_attention():
    """Config-only switch: the LM sample converges with the Pallas
    kernels exactly like the scan path."""
    from veles.config import root
    prng.seed_all(4242)
    from veles.znicz_tpu.models import transformer_lm
    saved_loader = root.lm.loader.to_dict()
    saved_model = root.lm.model.to_dict()
    saved_epochs = root.lm.decision.get("max_epochs")
    root.lm.loader.update({"minibatch_size": 32, "n_train": 256,
                           "n_valid": 64, "seq_len": 16, "vocab": 8,
                           "max_period": 4})
    root.lm.model.update({"dim": 32, "heads": 2, "layers": 1,
                          "ffn_hidden": 64, "attn_block": 16,
                          "attn_impl": "pallas", "moe_experts": 0,
                          "stacked": False})
    root.lm.decision.max_epochs = 5
    root.lm.parallel.update({"seq": 1, "model": 1, "data": 1,
                             "expert": 1, "pipe": 1})
    try:
        wf = transformer_lm.create_workflow(name="PallasLM")
        wf.initialize(device="xla")
        wf.run()
    finally:
        root.lm.loader.update(saved_loader)
        root.lm.model.update(saved_model)
        root.lm.decision.max_epochs = saved_epochs
    hist = [h["validation"]["metric"] for h in wf.decision.history]
    assert hist[-1] < hist[0], hist
