"""Pallas flash-attention kernels: exact parity with the scan-flash
and dense formulations (interpret mode on CPU; the same kernels run
natively on TPU), and the attention unit's pallas path against the
dense numpy oracle."""

import numpy
import pytest

import veles.prng as prng
from veles.memory import Array
from veles.znicz_tpu.ops.attention import MultiHeadAttention
from veles.znicz_tpu.parallel import flash, pallas_attention as PA

from tests.test_conv_stack import build, xla_forward, xla_backward


CASES = [
    dict(causal=True, s=64, block=32),
    dict(causal=False, s=64, block=32),
    dict(causal=True, s=128, block=64),
    dict(causal=True, s=64, block=64),   # single block
]


def _qkv(s, b=2, h=2, dh=8, seed=909):
    prng.seed_all(seed)
    gen = prng.get("pa")
    shape = (b, h, s, dh)
    return tuple(gen.normal(0, 1.0, shape).astype(numpy.float32)
                 for _ in range(3))


@pytest.mark.parametrize("case", CASES, ids=lambda c: str(c))
def test_pallas_fwd_matches_scan_flash(case):
    q, k, v = _qkv(case["s"])
    out_ref, lse_ref = flash.blocked_attention_fwd(
        q, k, v, causal=case["causal"], block=case["block"])
    out, lse = PA.flash_attention_fwd(
        q, k, v, causal=case["causal"], block_q=case["block"],
        block_k=case["block"], interpret=True)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(out_ref),
                          atol=2e-5), \
        numpy.abs(numpy.asarray(out) - numpy.asarray(out_ref)).max()
    assert numpy.allclose(numpy.asarray(lse), numpy.asarray(lse_ref),
                          atol=2e-5)


@pytest.mark.parametrize("case", CASES, ids=lambda c: str(c))
def test_pallas_bwd_matches_scan_flash(case):
    q, k, v = _qkv(case["s"])
    prng.seed_all(910)
    dout = prng.get("pa2").normal(0, 1.0, q.shape).astype(
        numpy.float32)
    out, lse = flash.blocked_attention_fwd(
        q, k, v, causal=case["causal"], block=case["block"])
    refs = flash.blocked_attention_bwd(
        q, k, v, out, lse, dout, causal=case["causal"],
        block=case["block"])
    got = PA.flash_attention_bwd(
        q, k, v, out, lse, dout, causal=case["causal"],
        block_q=case["block"], block_k=case["block"], interpret=True)
    for name, r, g in zip(("dq", "dk", "dv"), refs, got):
        assert numpy.allclose(numpy.asarray(g), numpy.asarray(r),
                              atol=2e-4), \
            (name,
             numpy.abs(numpy.asarray(g) - numpy.asarray(r)).max())


@pytest.mark.parametrize("case", CASES, ids=lambda c: str(c))
def test_pallas_bwd_fused_matches_two_kernel(case):
    """The single-pass dk/dv/dq kernel (dq accumulated in a revisited
    output ref across the sequential k-block grid — round 5, measured
    +38% on the backward at S=8k) must agree leaf-for-leaf with the
    retained two-kernel formulation."""
    q, k, v = _qkv(case["s"])
    prng.seed_all(911)
    dout = prng.get("pa3").normal(0, 1.0, q.shape).astype(
        numpy.float32)
    out, lse = PA.flash_attention_fwd(
        q, k, v, causal=case["causal"], block_q=case["block"],
        block_k=case["block"], interpret=True)
    two = PA.flash_attention_bwd(
        q, k, v, out, lse, dout, causal=case["causal"],
        block_q=case["block"], block_k=case["block"], interpret=True,
        fused=False)
    one = PA.flash_attention_bwd(
        q, k, v, out, lse, dout, causal=case["causal"],
        block_q=case["block"], block_k=case["block"], interpret=True,
        fused=True)
    for name, a, b in zip(("dq", "dk", "dv"), two, one):
        assert numpy.allclose(numpy.asarray(a), numpy.asarray(b),
                              atol=2e-5), \
            (name,
             numpy.abs(numpy.asarray(a) - numpy.asarray(b)).max())


@pytest.mark.parametrize("bq,bk", [(32, 16), (16, 32)],
                         ids=["bq>bk", "bq<bk"])
@pytest.mark.parametrize("causal", [True, False],
                         ids=["causal", "full"])
def test_pallas_unequal_blocks(bq, bk, causal):
    """UNEQUAL block_q/block_k exercise the hand-derived diagonal
    split boundaries (round 5: the floor/ceil clear points differ
    from the trivial qi/ki±1 values only here) — fwd vs the scan
    flash, and BOTH backward forms vs the scan backward."""
    s = 64
    q, k, v = _qkv(s)
    prng.seed_all(912)
    dout = prng.get("pa4").normal(0, 1.0, q.shape).astype(
        numpy.float32)
    out_ref, lse_ref = flash.blocked_attention_fwd(
        q, k, v, causal=causal, block=16)
    out, lse = PA.flash_attention_fwd(
        q, k, v, causal=causal, block_q=bq, block_k=bk,
        interpret=True)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(out_ref),
                          atol=2e-5)
    assert numpy.allclose(numpy.asarray(lse), numpy.asarray(lse_ref),
                          atol=2e-5)
    refs = flash.blocked_attention_bwd(
        q, k, v, out_ref, lse_ref, dout, causal=causal, block=16)
    for fused in (False, True):
        got = PA.flash_attention_bwd(
            q, k, v, out, lse, dout, causal=causal, block_q=bq,
            block_k=bk, interpret=True, fused=fused)
        for name, r, g in zip(("dq", "dk", "dv"), refs, got):
            assert numpy.allclose(numpy.asarray(g), numpy.asarray(r),
                                  atol=2e-4), \
                (fused, name,
                 numpy.abs(numpy.asarray(g) - numpy.asarray(r)).max())


@pytest.mark.parametrize("case", CASES, ids=lambda c: str(c))
def test_pallas_fwd_pipelined_matches_resident(case):
    """The DMA-pipelined forward (K/V in HBM, double-buffered block
    scratch) is a pure data-movement change: out and lse must match
    the resident-rows kernel to float tolerance."""
    q, k, v = _qkv(case["s"])
    out_ref, lse_ref = PA.flash_attention_fwd(
        q, k, v, causal=case["causal"], block_q=case["block"],
        block_k=case["block"], interpret=True)
    out, lse = PA.flash_attention_fwd(
        q, k, v, causal=case["causal"], block_q=case["block"],
        block_k=case["block"], interpret=True, pipeline=True)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(out_ref),
                          atol=2e-5), \
        numpy.abs(numpy.asarray(out) - numpy.asarray(out_ref)).max()
    assert numpy.allclose(numpy.asarray(lse), numpy.asarray(lse_ref),
                          atol=2e-5)


@pytest.mark.parametrize("bq,bk", [(32, 16), (16, 32)],
                         ids=["bq>bk", "bq<bk"])
def test_pallas_fwd_pipelined_unequal_blocks(bq, bk):
    """Unequal tiles stress the pipelined loop's causal bound (hi =
    cdiv over block_k while the DMA window is block_k-sized)."""
    q, k, v = _qkv(64)
    out_ref, lse_ref = flash.blocked_attention_fwd(
        q, k, v, causal=True, block=16)
    out, lse = PA.flash_attention_fwd(
        q, k, v, causal=True, block_q=bq, block_k=bk,
        interpret=True, pipeline=True)
    assert numpy.allclose(numpy.asarray(out), numpy.asarray(out_ref),
                          atol=2e-5)
    assert numpy.allclose(numpy.asarray(lse), numpy.asarray(lse_ref),
                          atol=2e-5)


def test_pallas_fwd_bf16_accumulate_numerics_gate():
    """THE gate for the bf16-accumulation experiment: against the f32-
    accumulated reference the output error must stay within the bf16
    input-rounding regime (~2^-8 relative on O(1) softmax-weighted
    averages), and the lse — whose statistics deliberately stay f32 —
    must remain exact. If a kernel change ever narrows the softmax
    chain too, this is the test that fires."""
    import jax.numpy as jnp
    q, k, v = _qkv(128, b=2, h=2, dh=16)
    for causal in (True, False):
        ref, lse_ref = PA.flash_attention_fwd(
            q, k, v, causal=causal, block_q=32, block_k=32,
            interpret=True)
        out, lse = PA.flash_attention_fwd(
            q, k, v, causal=causal, block_q=32, block_k=32,
            interpret=True, acc_dtype=jnp.bfloat16)
        err = numpy.abs(numpy.asarray(out) - numpy.asarray(ref)).max()
        assert err < 1.5e-2, err          # bf16 accumulation regime
        assert err > 0.0                  # the variant really ran
        assert numpy.allclose(numpy.asarray(lse),
                              numpy.asarray(lse_ref), atol=2e-5)


def test_attention_unit_pipelined_path():
    """attn_pipeline=True through the unit: forward matches the dense
    numpy oracle and the backward (which reads the cached out/lse —
    layout unchanged by the pipelined forward) still agrees."""
    wf, feed, fwd, gd, x, err, comp = build(
        MultiHeadAttention, input_shape=(2, 32, 16), gd_kwargs={},
        heads=2, attn_impl="pallas", attn_block_size=16,
        attn_pipeline=True)
    golden = numpy.array(fwd.output.mem)
    params0 = comp.gather_params()
    state0 = comp.gather_state()
    y = xla_forward(comp, feed, fwd, params0, x)
    assert numpy.allclose(numpy.asarray(y), golden, atol=3e-5)
    gd.numpy_run()
    ei_np = numpy.array(gd.err_input.mem)
    ei_x, _ = xla_backward(comp, feed, fwd, gd, params0, state0,
                           x, err)
    assert numpy.allclose(ei_np, numpy.asarray(ei_x), atol=3e-4)


def test_attention_unit_bf16_acc_path():
    """attn_acc='bf16' through the unit, forward AND backward: the
    experimental arm's gradients must stay within the bf16-acc
    numerics regime of the dense numpy oracle — a forward-only gate
    would let a backward-side regression ship on exactly the A/B run
    the knob exists for (the backward consumes the bf16-accumulated
    out/lse via delta = rowsum(dout*out))."""
    wf, feed, fwd, gd, x, err, comp = build(
        MultiHeadAttention, input_shape=(2, 32, 16), gd_kwargs={},
        heads=2, attn_impl="pallas", attn_block_size=16,
        attn_acc="bf16")
    golden = numpy.array(fwd.output.mem)
    params0 = comp.gather_params()
    state0 = comp.gather_state()
    y = xla_forward(comp, feed, fwd, params0, x)
    assert numpy.allclose(numpy.asarray(y), golden, atol=2e-2)
    gd.numpy_run()
    ei_np = numpy.array(gd.err_input.mem)
    ei_x, params1 = xla_backward(comp, feed, fwd, gd, params0, state0,
                                 x, err)
    assert numpy.allclose(ei_np, numpy.asarray(ei_x), atol=2e-2), \
        numpy.abs(ei_np - numpy.asarray(ei_x)).max()
    for pname in fwd.PARAMS:
        w1_np = getattr(fwd, pname).map_read().mem
        w1_x = numpy.asarray(params1[fwd.name][pname])
        assert numpy.allclose(w1_np, w1_x, atol=3e-2), pname


def test_attention_unit_rejects_bad_attn_acc():
    from veles.workflow import Workflow
    wf = Workflow(None, name="wf-acc")
    with pytest.raises(ValueError):
        MultiHeadAttention(wf, heads=2, attn_acc="fp64")


def test_attention_unit_rejects_inert_fwd_experiments():
    """attn_pipeline/attn_acc='bf16' on a dispatch that resolves to
    any non-pallas mode (dense/scan/ring) must raise loudly (like
    transformer_lm's stacked guard), never run the other kernel with
    a silently inert knob — the worst failure mode for an A/B."""
    from veles.workflow import Workflow
    wf = Workflow(None, name="wf-inert")
    for kwargs in ({"attn_pipeline": True}, {"attn_acc": "bf16"}):
        dense = MultiHeadAttention(wf, heads=2, **kwargs)
        with pytest.raises(ValueError, match="pallas"):
            dense._traced_mode(None, 32)
        scan = MultiHeadAttention(wf, heads=2, attn_impl="scan",
                                  attn_block_size=16, **kwargs)
        with pytest.raises(ValueError, match="pallas"):
            scan._traced_mode(None, 32)
        ring = MultiHeadAttention(wf, heads=2, **kwargs)
        ring.seq_mesh = object()
        with pytest.raises(ValueError, match="pallas"):
            ring._traced_mode(None, 32)
        # attn_acc='f32' is the explicit default, not an experiment
        MultiHeadAttention(wf, heads=2,
                           attn_acc="f32")._traced_mode(None, 32)


def test_attention_unit_pallas_path():
    """The unit with attn_impl='pallas': traced forward and backward
    must match the dense numpy oracle (different formulation, same
    math)."""
    wf, feed, fwd, gd, x, err, comp = build(
        MultiHeadAttention, input_shape=(2, 32, 16), gd_kwargs={},
        heads=2, attn_impl="pallas", attn_block_size=16)
    golden = numpy.array(fwd.output.mem)          # dense numpy oracle
    params0 = comp.gather_params()
    state0 = comp.gather_state()
    y = xla_forward(comp, feed, fwd, params0, x)
    assert numpy.allclose(numpy.asarray(y), golden, atol=3e-5)
    gd.numpy_run()                                # dense oracle bwd
    ei_np = numpy.array(gd.err_input.mem)
    ei_x, params1 = xla_backward(comp, feed, fwd, gd, params0, state0,
                                 x, err)
    assert numpy.allclose(ei_np, numpy.asarray(ei_x), atol=3e-4), \
        numpy.abs(ei_np - numpy.asarray(ei_x)).max()
    for pname in fwd.PARAMS:
        w1_np = getattr(fwd, pname).map_read().mem
        w1_x = numpy.asarray(params1[fwd.name][pname])
        assert numpy.allclose(w1_np, w1_x, atol=5e-4), pname


def test_lm_trains_with_pallas_attention():
    """Config-only switch: the LM sample converges with the Pallas
    kernels exactly like the scan path."""
    from veles.config import root
    prng.seed_all(4242)
    from veles.znicz_tpu.models import transformer_lm
    saved_loader = root.lm.loader.to_dict()
    saved_model = root.lm.model.to_dict()
    saved_epochs = root.lm.decision.get("max_epochs")
    root.lm.loader.update({"minibatch_size": 32, "n_train": 256,
                           "n_valid": 64, "seq_len": 16, "vocab": 8,
                           "max_period": 4})
    root.lm.model.update({"dim": 32, "heads": 2, "layers": 1,
                          "ffn_hidden": 64, "attn_block": 16,
                          "attn_impl": "pallas", "moe_experts": 0,
                          "stacked": False})
    root.lm.decision.max_epochs = 5
    root.lm.parallel.update({"seq": 1, "model": 1, "data": 1,
                             "expert": 1, "pipe": 1})
    try:
        wf = transformer_lm.create_workflow(name="PallasLM")
        wf.initialize(device="xla")
        wf.run()
    finally:
        root.lm.loader.update(saved_loader)
        root.lm.model.update(saved_model)
        root.lm.decision.max_epochs = saved_epochs
    hist = [h["validation"]["metric"] for h in wf.decision.history]
    assert hist[-1] < hist[0], hist
