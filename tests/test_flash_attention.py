"""Blocked (flash-style) single-chip attention: exact parity with the
dense path, and usable from the LM config (long-context story,
parallel/flash.py)."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root


@pytest.mark.parametrize("causal", [True, False])
def test_blocked_matches_dense_fwd_bwd(causal):
    import jax
    import jax.numpy as jnp
    from veles.znicz_tpu.parallel import flash

    gen = prng.get("flash_test")
    b, h, s, dh = 2, 3, 64, 8
    q, k, v = (gen.normal(0, 1, (b, h, s, dh)) for _ in range(3))
    dout = gen.normal(0, 1, (b, h, s, dh))

    def dense(q, k, v):
        scale = 1.0 / numpy.sqrt(dh)
        sc = (q @ jnp.swapaxes(k, -1, -2)) * scale
        if causal:
            mask = jnp.triu(jnp.full((s, s), -1e9, jnp.float32), 1)
            sc = sc + mask
        p = jax.nn.softmax(sc, axis=-1)
        return p @ v

    out_d = dense(q, k, v)
    out_b, lse = flash.blocked_attention_fwd(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        causal=causal, block=16)
    assert numpy.allclose(numpy.asarray(out_b),
                          numpy.asarray(out_d), atol=2e-5)

    # backward vs jax.grad of the dense formulation
    def loss(args):
        return (dense(*args) * dout).sum()
    gq, gk, gv = jax.grad(loss)((jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v)))
    dq, dk, dv = flash.blocked_attention_bwd(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), out_b, lse,
        jnp.asarray(dout), causal=causal, block=16)
    for got, want, name in ((dq, gq, "dq"), (dk, gk, "dk"),
                            (dv, gv, "dv")):
        assert numpy.allclose(numpy.asarray(got), numpy.asarray(want),
                              atol=3e-4), name


def test_block_must_divide():
    import jax.numpy as jnp
    from veles.znicz_tpu.parallel import flash
    q = jnp.zeros((1, 1, 30, 4))
    with pytest.raises(ValueError, match="does not divide"):
        flash.blocked_attention_fwd(q, q, q, block=16)


def test_mha_unit_blocked_path_matches_dense():
    """The attention UNIT with attn_block_size set (fwd + bwd) equals
    its own dense path."""
    from veles.znicz_tpu.ops.attention import MultiHeadAttention
    from tests.test_conv_stack import build, xla_forward, xla_backward

    prng.seed_all(123)
    wf, feed, fwd, gd, x, err, comp = build(
        MultiHeadAttention, input_shape=(2, 32, 8), gd_kwargs={},
        heads=2)
    params0 = comp.gather_params()
    state0 = comp.gather_state()
    y_dense = numpy.asarray(xla_forward(comp, feed, fwd, params0, x))
    ei_dense, params_dense = xla_backward(
        comp, feed, fwd, gd, params0, state0, x, err)

    fwd.attn_block_size = 8
    y_blk = numpy.asarray(xla_forward(comp, feed, fwd, params0, x))
    ei_blk, params_blk = xla_backward(
        comp, feed, fwd, gd, params0, state0, x, err)
    fwd.attn_block_size = None

    assert numpy.allclose(y_blk, y_dense, atol=3e-5)
    assert numpy.allclose(numpy.asarray(ei_blk),
                          numpy.asarray(ei_dense), atol=3e-4)
    for pname in params_dense[fwd.name]:
        assert numpy.allclose(
            numpy.asarray(params_blk[fwd.name][pname]),
            numpy.asarray(params_dense[fwd.name][pname]),
            atol=3e-4), pname


def test_lm_blocked_attention_from_config():
    """root.lm.model.attn_block engages the blocked path; training
    trajectory matches dense."""
    from veles.znicz_tpu.models import transformer_lm
    from veles.znicz_tpu.ops.attention import MultiHeadAttention

    def run(name, attn_block):
        prng.seed_all(999)
        saved_loader = root.lm.loader.to_dict()
        saved_epochs = root.lm.decision.get("max_epochs")
        root.lm.loader.update({"minibatch_size": 32, "n_train": 256,
                               "n_valid": 64})
        root.lm.decision.max_epochs = 2
        root.lm.model.attn_block = attn_block
        try:
            wf = transformer_lm.create_workflow(name=name)
            wf.initialize(device="cpu")
            wf.run()
        finally:
            root.lm.model.attn_block = None
            root.lm.loader.update(saved_loader)
            root.lm.decision.max_epochs = saved_epochs
        return wf

    wf_d = run("LMDenseAttn", None)
    wf_b = run("LMBlockAttn", 8)
    mha = [f for f in wf_b.forwards
           if isinstance(f, MultiHeadAttention)]
    assert mha and all(f.attn_block_size == 8 for f in mha)
    h_d = [h["validation"]["metric"] for h in wf_d.decision.history]
    h_b = [h["validation"]["metric"] for h in wf_b.decision.history]
    for a, b in zip(h_b, h_d):
        assert abs(a - b) < 0.05, (h_b, h_d)
