"""Autoregressive generation: KV-cached decode == naive full-forward
greedy decode (exactness), trained-model continuation quality on the
periodic task, and the stacked-model path."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root
from veles.znicz_tpu.generate import generate


def _train_lm(name, seed=99, stacked=False, epochs=8):
    prng.seed_all(seed)
    from veles.znicz_tpu.models import transformer_lm
    saved_loader = root.lm.loader.to_dict()
    saved_model = root.lm.model.to_dict()
    saved_epochs = root.lm.decision.get("max_epochs")
    root.lm.loader.update({"minibatch_size": 32, "n_train": 512,
                           "n_valid": 128, "seq_len": 16, "vocab": 8,
                           "max_period": 4})
    root.lm.model.update({"dim": 32, "heads": 2, "layers": 2,
                          "ffn_hidden": 64, "moe_experts": 0,
                          "attn_block": None, "attn_impl": None,
                          "stacked": stacked})
    root.lm.decision.max_epochs = epochs
    root.lm.parallel.update({"seq": 1, "model": 1, "data": 1,
                             "expert": 1, "pipe": 1})
    try:
        wf = transformer_lm.create_workflow(name=name)
        wf.initialize(device="xla")
        wf.run()
    finally:
        root.lm.loader.update(saved_loader)
        root.lm.model.update(saved_model)
        root.lm.decision.max_epochs = saved_epochs
    return wf


def _naive_greedy(wf, prompt, n_tokens):
    """Oracle: re-run the FULL forward on the growing sequence each
    step, take argmax of the last position (numpy oracle path)."""
    ids = numpy.array(prompt, numpy.int32)
    out = []
    loader = wf.loader
    seq_len = loader.minibatch_data.shape[1]
    for _ in range(n_tokens):
        cur = min(ids.shape[1], seq_len)
        window = ids[:, -cur:]
        # RIGHT-pad to the static shape; causal attention means the
        # tail padding cannot influence position cur-1
        feed = numpy.pad(window, ((0, 0), (0, seq_len - cur)))
        mb = loader.minibatch_data.shape[0]
        batch = numpy.zeros((mb, seq_len), numpy.int32)
        batch[:feed.shape[0]] = feed
        loader.minibatch_data.map_invalidate()
        loader.minibatch_data.mem[...] = batch
        for f in wf.forwards:
            f.numpy_run()
        logits = wf.forwards[-1].output.map_read().mem
        nxt = logits[:feed.shape[0], cur - 1, :].argmax(-1)
        out.append(nxt)
        ids = numpy.concatenate([ids, nxt[:, None]], axis=1)
    return numpy.stack(out, axis=1).astype(numpy.int32)


@pytest.fixture(scope="module")
def lm():
    return _train_lm("GenLM")


def test_cached_decode_matches_naive(lm):
    """Greedy KV-cached generation == re-run-everything greedy
    decode, token for token."""
    prompt = numpy.array([[1, 2, 3, 1, 2, 3, 1, 2],
                          [5, 6, 5, 6, 5, 6, 5, 6]], numpy.int32)
    lm.xla_step.sync_host()
    got = generate(lm, prompt, 6, temperature=0.0)
    want = _naive_greedy(lm, prompt, 6)
    assert got.shape == (2, 6)
    assert (got == want).all(), (got, want)


def test_trained_model_continues_patterns(lm):
    """The periodic-copy task is solvable by attention: the trained
    model's greedy continuation must mostly follow the pattern."""
    gen = prng.get("gen_eval")
    n, correct, total = 8, 0, 0
    prompts, expects = [], []
    for i in range(n):
        p = int(gen.randint(2, 5))
        pattern = gen.randint(0, 8, p)
        seq = numpy.tile(pattern, 18 // p + 2)
        prompts.append(seq[:12])
        expects.append(seq[12:18])
    got = generate(lm, numpy.stack(prompts), 6, temperature=0.0)
    for row, want in zip(got, expects):
        correct += int((row == want).sum())
        total += 6
    assert correct / total > 0.7, (correct, total, got)


def test_generate_stacked_lm():
    """Generation walks the fused transformer_stack unit too."""
    wf = _train_lm("GenStack", seed=77, stacked=True, epochs=6)
    prompt = numpy.array([[1, 2, 1, 2, 1, 2]], numpy.int32)
    wf.xla_step.sync_host()
    got = generate(wf, prompt, 5, temperature=0.0)
    want = _naive_greedy(wf, prompt, 5)
    assert (got == want).all(), (got, want)


def test_generate_temperature_sampling(lm):
    """temperature > 0 samples (deterministic under a fixed key) and
    stays inside the vocabulary."""
    import jax
    prompt = numpy.array([[1, 2, 3, 4]], numpy.int32)
    a = generate(lm, prompt, 8, temperature=1.0,
                 key=jax.random.PRNGKey(7))
    b = generate(lm, prompt, 8, temperature=1.0,
                 key=jax.random.PRNGKey(7))
    assert (a == b).all()
    assert a.min() >= 0 and a.max() < 8


def test_generate_zero_tokens_and_compile_cache(lm):
    """n_tokens=0 returns (B, 0); repeated same-shape calls reuse the
    compiled decoder."""
    prompt = numpy.array([[1, 2, 3, 4]], numpy.int32)
    assert generate(lm, prompt, 0).shape == (1, 0)
    generate(lm, prompt, 4)
    n = len(lm._generate_jit_cache)
    generate(lm, prompt, 4)
    assert len(lm._generate_jit_cache) == n


def test_generate_cache_keys_by_weakref_and_evicts_dead(lm):
    """ISSUE 11 satellite: the compiled-decoder cache keys hold
    WEAKREFS to the step units — a freed unit's reallocated id can
    never alias a stale decoder — and entries whose units died are
    evicted on the next generate() call."""
    import gc
    from veles.znicz_tpu.generate import _cache_key, _evict_dead

    class U:
        pass

    live, doomed = U(), U()
    cache = {_cache_key((1, 4, 4, 0.0, None, None),
                        [("embed", live, None)]): "keep",
             _cache_key((1, 4, 4, 0.0, None, None),
                        [("embed", doomed, None)]): "drop"}
    # same sig + same live units -> the SAME entry (weakrefs compare
    # by referent identity), so repeated calls hit the cache
    assert cache[_cache_key((1, 4, 4, 0.0, None, None),
                            [("embed", live, None)])] == "keep"
    del doomed
    gc.collect()
    _evict_dead(cache)
    assert list(cache.values()) == ["keep"]
    # a NEW unit object never matches the dead entry's key even if it
    # reuses the freed id — and the real cache evicts as it runs
    prompt = numpy.array([[1, 2, 3, 4]], numpy.int32)
    generate(lm, prompt, 3)
    n = len(lm._generate_jit_cache)
    assert n >= 1
    for key in lm._generate_jit_cache:
        assert all(r() is not None for r in key[-1])
    generate(lm, prompt, 3)
    assert len(lm._generate_jit_cache) == n


def test_generate_top_k_top_p(lm):
    """top_k=1 sampling must equal greedy whatever the temperature;
    top_p near 0 likewise (only the top token survives)."""
    import jax
    prompt = numpy.array([[1, 2, 3, 1, 2, 3, 1, 2]], numpy.int32)
    greedy = generate(lm, prompt, 6, temperature=0.0)
    k1 = generate(lm, prompt, 6, temperature=1.5,
                  key=jax.random.PRNGKey(3), top_k=1)
    assert (k1 == greedy).all(), (k1, greedy)
    p0 = generate(lm, prompt, 6, temperature=1.5,
                  key=jax.random.PRNGKey(3), top_p=1e-6)
    assert (p0 == greedy).all(), (p0, greedy)
