"""Closed-loop continual training (ISSUE 16): streaming ingest ->
durable trainer -> verified-checkpoint rolling fleet refresh, with
staleness as the SLO.

Unit level first (round cursor, resume parity, shard leases, the
refresh poll's diverged gate, rolling-refresh policy, top rendering —
no sockets where possible), then the chaos run (BrownoutProxy
black-holes the HTTP ingest source -> the staleness burn-rate alert
fires and /readyz names the objective -> restore resolves), then the
slow-marked multi-process acceptance loop (continual trainer + two
``velescli serve`` replicas rolled one at a time with zero failed
requests and a staleness drop, diverged checkpoint never rolled out).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy
import pytest

import veles.prng as prng
from veles import continual, fleet, telemetry
from veles.config import root
from veles.loader.stream import ArraySource, ContinualStreamLoader
from veles.workflow import Workflow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def wait_until(fn, timeout=30.0, interval=0.02, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = fn()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError("timed out waiting for %s" % what)


def _source(n=256, dim=16, seed=5):
    rng = numpy.random.RandomState(seed)
    return ArraySource(
        rng.uniform(-1, 1, (n, dim)).astype(numpy.float32),
        rng.randint(0, 4, n).astype(numpy.int32))


def _loader(name="loader", source=None, **kwargs):
    kwargs.setdefault("minibatch_size", 32)
    kwargs.setdefault("round_samples", 128)
    kwargs.setdefault("valid_samples", 32)
    wf = Workflow(None, name="CW_" + name)
    ld = ContinualStreamLoader(
        wf, name=name, source=source or _source(), **kwargs)
    ld.initialize()
    return ld


def _serve_round(ld, collect_train=False):
    """Drive ld.run() through one full round; -> train indices (or
    [])."""
    out = []
    while True:
        ld.run()
        if collect_train and int(ld.minibatch_class) == 2:
            out.extend(
                ld.minibatch_indices.mem[:int(ld.minibatch_size)]
                .tolist())
        if bool(ld.epoch_ended):
            return out


# -- the streaming loader ----------------------------------------------


def test_rounds_advance_cursor_and_serve_stream_order():
    src = _source()
    ld = _loader(source=src)
    try:
        assert ld.cursor_base == 32        # head fed the pinned valid
        r1, first_batch = [], None
        while True:
            ld.run()
            if int(ld.minibatch_class) == 2:
                size = int(ld.minibatch_size)
                r1.extend(ld.minibatch_indices.mem[:size].tolist())
                if first_batch is None:
                    first_batch = numpy.array(
                        ld.minibatch_data.mem[:size])
            if bool(ld.epoch_ended):
                break
        assert ld.cursor_base == 160
        r2 = _serve_round(ld, collect_train=True)
        assert ld.cursor_base == 288
        off = ld.class_offset(2)
        assert r1 == list(range(off + 32, off + 160))
        assert r2 == list(range(off + 160, off + 288))
        # the round's data really is the stream window (position p
        # serves source row p, through the prefetch plane): the first
        # train minibatch of round 1 covers stream positions 32..63
        numpy.testing.assert_array_equal(
            first_batch, src.fetch(32, 32)["data"])
        # the bounded buffer never grows past its cap
        assert len(ld._blocks) <= ld.prefetch_blocks
        assert ld.last_ingest_wall > 0
    finally:
        ld.stop()


def test_checkpoint_cursor_resume_no_replay_no_skip():
    """The satellite contract: a resumed run continues at the next
    round's first position — the restored loader serves EXACTLY the
    round the original would have served next."""
    a = _loader(name="a")
    try:
        _serve_round(a)
        state = a.get_state()
        assert state["stream_cursor"]["cursor_base"] == 160
        next_round = _serve_round(a, collect_train=True)
    finally:
        a.stop()
    b = _loader(name="b")
    try:
        b.set_state(state)
        resumed = _serve_round(b, collect_train=True)
    finally:
        b.stop()
    assert resumed == next_round


def test_zlint_checkpoint_state_rule_passes_without_pragma():
    from veles.analysis import analyze_paths
    findings = analyze_paths(
        [os.path.join(REPO, "veles", "loader", "stream.py")],
        select=["checkpoint-state"])
    assert findings == []


def test_shard_assignment_is_sticky_and_steals_orphans():
    ld = _loader(shards=2, valid_samples=0, round_samples=128)
    try:
        ld.master_start_epoch()
        assert ld.cursor_base == 128       # queue filled == claimed
        mb = ld.max_minibatch_size

        def shard_of(job):
            return (int(job[1][0]) // mb) % 2

        j1 = ld.generate_data_for_slave("s1")
        j2 = ld.generate_data_for_slave("s2")
        assert shard_of(j1) == ld._slave_shards["s1"]
        assert shard_of(j2) == ld._slave_shards["s2"]
        assert shard_of(j1) != shard_of(j2)
        # each slave keeps pulling only its own shard while both live
        j1b = ld.generate_data_for_slave("s1")
        assert shard_of(j1b) == shard_of(j1)
        # s2 dies: its lease is released and s1 STEALS the orphaned
        # shard instead of wedging the round
        ld.drop_slave("s2")
        served = {tuple(j[1]) for j in (j1, j1b)}
        while True:
            job = ld.generate_data_for_slave("s1")
            if job is None:
                break
            assert tuple(job[1]) not in served
            served.add(tuple(job[1]))
        assert not ld._pending_jobs
        assert len(served) == 128 // mb
    finally:
        ld.stop()


def test_fetch_failures_counted_and_retried():
    class Flaky(ArraySource):
        def __init__(self, *args):
            super().__init__(*args)
            self.failures = 2

        def fetch(self, start, count):
            if start >= 32 and self.failures:
                self.failures -= 1
                raise OSError("synthetic ingest outage")
            return super().fetch(start, count)

    rng = numpy.random.RandomState(3)
    src = Flaky(rng.uniform(-1, 1, (64, 8)).astype(numpy.float32),
                rng.randint(0, 4, 64).astype(numpy.int32))
    ld = _loader(source=src, fetch_retry_s=0.01)
    try:
        _serve_round(ld)
        assert src.failures == 0
        assert telemetry.get_registry().counter_total(
            "veles_stream_fetch_failures_total") >= 2.0
    finally:
        ld.stop()


# -- the trainer loop --------------------------------------------------


def _continual_workflow(name, rounds_data=1024, snapdir=None):
    import veles.znicz_tpu.models.mnist  # noqa: populates root.mnist
    from veles.znicz_tpu.standard_workflow import StandardWorkflow
    prng.seed_all(1313)
    rng = numpy.random.RandomState(7)
    data = rng.uniform(-1, 1, (rounds_data, 784)).astype(numpy.float32)
    labels = rng.randint(0, 10, rounds_data).astype(numpy.int32)
    extra = {}
    if snapdir:
        extra["snapshotter_config"] = {"directory": snapdir}
    wf = StandardWorkflow(
        None, name=name, layers=root.mnist.layers,
        loader_factory=lambda w: ContinualStreamLoader(
            w, name="loader", minibatch_size=32,
            source=ArraySource(data, labels),
            round_samples=128, valid_samples=64),
        decision_config={"max_epochs": 1, "fail_iterations": 50},
        **extra)
    wf.initialize(device="cpu")
    return wf


def test_continual_loop_runs_rounds_and_publishes_staleness():
    wf = _continual_workflow("ContinualRounds")
    done = continual.continual_loop(wf, rounds=2)
    assert done == 2
    assert int(wf.decision.epoch_number) == 2
    # successive rounds consumed successive stream windows
    assert wf.loader.cursor_base == 64 + 2 * 128
    # the ingest clock is registered process-wide and the trainer
    # staleness point reads near-zero right after a round
    wall = continual.ingest_wall()
    assert wall and time.time() - wall < 60.0
    reg = telemetry.get_registry()
    assert reg.counter_total("veles_continual_rounds_total") == 2.0
    stale = reg.gauge(continual.STALENESS_FAMILY,
                      labels=("point",)).labels("trainer").value
    assert 0.0 <= stale < 60.0
    # patience is disarmed: a shifting stream must not trip the
    # no-improvement stop between rounds
    assert wf.decision.fail_iterations == float("inf")


def test_checkpoints_carry_ingest_wall(tmp_path):
    from veles import snapshotter as S
    wf = _continual_workflow("ContinualSnap", snapdir=str(tmp_path))
    continual.continual_loop(wf, rounds=1)
    wf.snapshotter.export_snapshot(slot="current")
    infos = [i for i in S.scan_checkpoints(str(tmp_path))
             if i.status == "valid"]
    assert infos
    newest = infos[0]
    assert newest.ingest_wall is not None
    assert abs(newest.ingest_wall
               - wf.loader.last_ingest_wall) < 1e-6
    assert newest.health_verdict == "healthy"


# -- serving refresh + rolling fleet refresh ---------------------------


@pytest.fixture(scope="module")
def mnist_archive(tmp_path_factory):
    """An (untrained) exported MNIST MLP archive — the serving side's
    model; params are what checkpoints must shape-match."""
    prng.seed_all(77)
    from veles.znicz_tpu.models import mnist
    saved = {k: root.mnist.loader.get(k)
             for k in ("minibatch_size", "n_train", "n_valid")}
    root.mnist.loader.update({"minibatch_size": 50, "n_train": 200,
                              "n_valid": 50})
    base = tmp_path_factory.mktemp("continual_serving")
    try:
        wf = mnist.create_workflow(name="ContinualServe")
        wf.initialize(device="numpy")
        archive = str(base / "archive")
        wf.export_inference(archive)
        x = wf.loader.original_data.mem[:4].astype(numpy.float32)
        yield {"archive": archive, "x": x}
    finally:
        root.mnist.loader.update(saved)


def _write_ckpt(store_dir, name, params, scale, wall,
                verdict="healthy", ingest_wall=None):
    from veles import snapshotter as S
    store = S.store_for_base(str(store_dir), create=True)
    tree = {"params": {
        uname: {k: numpy.asarray(v, numpy.float32) * scale
                for k, v in attrs.items()}
        for uname, attrs in params.items()}}
    extra = {"wall_time": float(wall),
             "model_health": {"verdict": verdict,
                              "reasons": [] if verdict == "healthy"
                              else ["nonfinite_wire:fc"]}}
    if ingest_wall is not None:
        extra["ingest_wall"] = float(ingest_wall)
    S.write_checkpoint(store, name, tree, slot="current",
                       extra_meta=extra)


def test_refresh_newest_loads_healthy_and_skips_diverged(
        tmp_path, mnist_archive):
    from veles.serving import ModelRegistry
    reg = ModelRegistry(backend="numpy")
    try:
        entry = reg.load("mnist", mnist_archive["archive"],
                         refresh_store=str(tmp_path))
        params = entry.model.params
        t0 = time.time()
        _write_ckpt(tmp_path, "m_current-00000001.ckpt.npz.gz",
                    params, 0.5, t0 - 10, ingest_wall=t0 - 12)
        # the poisoned update: NEWEST blob, diverged verdict
        _write_ckpt(tmp_path, "m_current-00000002.ckpt.npz.gz",
                    params, 99.0, t0, verdict="diverged")
        before = telemetry.get_registry().counter_total(
            "veles_checkpoint_diverged_skips_total") or 0.0
        loaded = reg.refresh_newest("mnist")
        assert loaded and loaded.endswith("00000001.ckpt.npz.gz")
        entry = reg.get("mnist")
        assert entry.model.checkpoint_meta["wall_time"] == t0 - 10
        assert entry.model.checkpoint_meta["ingest_wall"] == t0 - 12
        assert telemetry.get_registry().counter_total(
            "veles_checkpoint_diverged_skips_total") == before + 1.0
        skips = [e for e in telemetry.tracer.recent_events()
                 if e["event"] == "refresh_skipped_diverged"]
        assert skips and skips[-1]["checkpoint"] == \
            "m_current-00000002.ckpt.npz.gz"
        # nothing newer (and the diverged blob stays refused): no-op
        assert reg.refresh_newest("mnist") is None
        # the scrape-side gauges carry the served wall + staleness
        g = telemetry.get_registry().gauge(
            "veles_serving_checkpoint_wall_seconds",
            labels=("model",)).labels("mnist")
        assert g.value == t0 - 10
        stale = telemetry.get_registry().gauge(
            continual.STALENESS_FAMILY,
            labels=("point",)).labels("serving:mnist").value
        assert 10.0 <= stale < 60.0
    finally:
        reg.close()


def test_refresh_http_endpoint(tmp_path, mnist_archive):
    from veles.serving import ModelRegistry
    from veles.serving.frontend import ServingFrontend
    reg = ModelRegistry(backend="numpy")
    front = None
    try:
        # the refresh plane only admits targets inside stores the
        # OPERATOR configured at load time (zlint untrusted-path):
        # attach the store here, not via the HTTP body
        entry = reg.load("mnist", mnist_archive["archive"],
                         refresh_store=str(tmp_path))
        t0 = time.time()
        _write_ckpt(tmp_path, "m_current-00000001.ckpt.npz.gz",
                    entry.model.params, 0.5, t0 - 5,
                    ingest_wall=t0 - 6)
        front = ServingFrontend(reg, port=0)
        base = "http://127.0.0.1:%d" % front.port
        req = urllib.request.Request(
            base + "/v1/models/mnist/refresh",
            data=json.dumps({"store": str(tmp_path)}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            doc = json.load(resp)
        assert doc["loaded"].endswith("00000001.ckpt.npz.gz")
        assert doc["checkpoint_meta"]["ingest_wall"] == t0 - 6
        # an explicitly-named diverged checkpoint is refused with 409
        _write_ckpt(tmp_path, "m_current-00000002.ckpt.npz.gz",
                    entry.model.params, 9.0, t0, verdict="diverged")
        req = urllib.request.Request(
            base + "/v1/models/mnist/refresh",
            data=json.dumps({"checkpoint": str(
                tmp_path / "m_current-00000002.ckpt.npz.gz")}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 409
        # a refresh target OUTSIDE every configured store is refused
        # with 400 before any filesystem access
        req = urllib.request.Request(
            base + "/v1/models/mnist/refresh",
            data=json.dumps(
                {"store": "/etc"}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 400
        assert "outside" in json.load(err.value)["error"]
    finally:
        if front is not None:
            front.close()
        reg.close()


def test_controller_readmit_and_ckpt_wall_from_rows():
    from veles.router import ADMITTED, DRAINING, FleetController
    urls = ["http://a:1", "http://b:1"]
    ctl = FleetController(urls, interval=3600)

    def row(url, **metrics):
        return {"url": url, "reachable": True, "ready": True,
                "firing": [], "reasons": [], "metrics": metrics}

    ctl.tick(rows=[row("http://a:1", serving_ckpt_wall=123.0,
                       staleness_seconds=42.0),
                   row("http://b:1")])          # pre-PR-16 replica
    doc = {b["url"]: b for b in ctl.status_doc["backends"]}
    assert doc["http://a:1"]["ckpt_wall"] == 123.0
    assert doc["http://a:1"]["staleness"] == 42.0
    assert doc["http://b:1"]["ckpt_wall"] is None
    # drain -> readmit is a clean round trip; readmit refuses other
    # states (it must not shortcut the half-open probe)
    assert ctl.drain("http://a:1") == 0
    with ctl._lock:
        assert ctl._replicas["http://a:1"].state == DRAINING
    assert ctl.readmit("http://a:1") is True
    with ctl._lock:
        assert ctl._replicas["http://a:1"].state == ADMITTED
    assert ctl.readmit("http://a:1") is False
    assert ctl.readmit("http://nope:1") is False
    ctl.close()


def test_rolling_refresh_never_rolls_diverged(tmp_path, mnist_archive):
    """The orchestrator's poisoned-update gate at unit level: with the
    newest blob diverged, the newest HEALTHY wall is what replicas are
    compared against — replicas already there are left alone."""
    from veles.router import FleetController, RollingRefresh
    from veles.serving import ModelRegistry
    reg = ModelRegistry(backend="numpy")
    try:
        params = reg.load(
            "mnist", mnist_archive["archive"]).model.params
    finally:
        reg.close()
    t0 = time.time()
    _write_ckpt(tmp_path, "m_current-00000001.ckpt.npz.gz",
                params, 0.5, t0 - 10)
    _write_ckpt(tmp_path, "m_current-00000002.ckpt.npz.gz",
                params, 99.0, t0, verdict="diverged")
    rr = RollingRefresh(str(tmp_path), "mnist", period_s=0.0)
    info = rr._newest_healthy()
    assert info.name == "m_current-00000001.ckpt.npz.gz"
    skips = [e for e in telemetry.tracer.recent_events()
             if e["event"] == "refresh_skipped_diverged"]
    assert skips and skips[-1]["checkpoint"] == \
        "m_current-00000002.ckpt.npz.gz"
    ctl = FleetController(["http://a:1"], interval=3600)
    ctl.tick(rows=[{"url": "http://a:1", "reachable": True,
                    "ready": True, "firing": [], "reasons": [],
                    "metrics": {"serving_ckpt_wall": t0 - 10}}])
    # evaluate spawns the scan thread; it must decide "nothing to
    # roll" (replica already serves the newest HEALTHY wall)
    rr.evaluate(ctl)
    wait_until(lambda: not (rr._thread and rr._thread.is_alive()),
               what="refresh scan to finish")
    assert rr.describe()["rolls"] == 0
    with ctl._lock:
        assert ctl._replicas["http://a:1"].state == "admitted"
    ctl.close()


def test_top_renders_staleness_and_last_refresh_and_degrades():
    snap = {"fleet": {"targets": 2, "reachable": 2, "ready": 2,
                      "slaves": 0, "firing_slos": []},
            "targets": [
                {"url": "http://t:1", "reachable": True, "ready": True,
                 "role": "process",
                 "metrics": {"staleness_seconds": 42.0}},
                {"url": "http://r:1", "reachable": True, "ready": True,
                 "role": "router", "metrics": {},
                 "router": {"backends": [
                     {"url": "http://t:1", "state": "admitted"},
                     {"url": "http://u:1", "state": "admitted"}],
                     "rolling_refresh": {
                         "last": {"replica": "http://u:1",
                                  "outcome": "ok"}}}},
            ]}
    out = fleet.render_snapshot(snap)
    assert "staleness 42s" in out
    assert "last refresh: replica 1 (ok)" in out
    # pre-PR-16 rows (no staleness key, no rolling_refresh doc) must
    # only degrade
    for row in snap["targets"]:
        row["metrics"] = {}
        if "router" in row:
            row["router"].pop("rolling_refresh")
    out = fleet.render_snapshot(snap)
    assert "staleness" not in out and "last refresh" not in out


def test_fleet_metric_max_vs_total():
    metrics = {("veles_staleness_seconds", (("point", "trainer"),)): 7.0,
               ("veles_staleness_seconds",
                (("point", "serving:m"),)): 41.0}
    assert fleet.metric_max(metrics, "veles_staleness_seconds") == 41.0
    assert fleet.metric_max(metrics, "veles_nope") is None


# -- chaos: ingest black-hole -> staleness alert -----------------------


def test_blackhole_ingest_fires_staleness_slo_and_resolves():
    """The loop-stall drill: BrownoutProxy black-holes the HTTP ingest
    wire; staleness climbs past the objective, the burn-rate alert
    fires and /readyz names it; restoring the wire lets the round
    finish and the alert resolve."""
    from veles.chaos import BrownoutProxy
    from veles.health import HealthMonitor
    from veles.reactor import HttpServer
    src = _source(n=64, dim=8)
    server = HttpServer("127.0.0.1", 0,
                        continual.stream_handler(src),
                        name="ingest")
    proxy = BrownoutProxy("127.0.0.1:%d" % server.port)
    mon = HealthMonitor(interval=3600)   # ticked manually
    ld = None
    try:
        http_src = continual.HttpStreamSource(proxy.url, timeout=0.3)
        ld = _loader(source=http_src, minibatch_size=16,
                     round_samples=64, valid_samples=16,
                     fetch_retry_s=0.05, prefetch_blocks=2)
        continual.register_ingest_clock(
            lambda: ld.last_ingest_wall)
        continual.install_point_gauge("trainer",
                                      continual.ingest_wall)
        assert continual.install_staleness_slo(
            threshold=0.3, monitor=mon, fast_window=0.5,
            slow_window=1.0) == 1
        assert continual.install_staleness_slo(
            threshold=0.3, monitor=mon) == 0    # idempotent
        _serve_round(ld)
        mon.tick()
        assert not mon.slos()[0].firing
        def tick_firing():
            mon.tick()
            return mon.slos()[0].firing

        # black hole: connections wedge, bytes vanish — the producer
        # retries forever while the round stalls mid-flight
        proxy.set_black_hole(True)
        rounds, stop_evt = [0], threading.Event()

        def round_pump():
            try:
                while not stop_evt.is_set():
                    _serve_round(ld)
                    rounds[0] += 1
            except RuntimeError:
                pass    # loader stopped by the finally block

        runner = threading.Thread(target=round_pump, daemon=True)
        runner.start()
        wait_until(tick_firing, timeout=30.0, interval=0.1,
                   what="staleness alert to fire")
        assert rounds[0] == 0, "round finished through a black hole"
        ok, reasons = mon.ready_state()
        assert ok is False
        assert any("staleness" in r for r in reasons)
        assert telemetry.get_registry().counter_total(
            "veles_stream_fetch_failures_total") >= 1.0
        # restore: the wedged round completes, ingest flows again and
        # good samples age the violation out of both windows
        proxy.restore()
        wait_until(lambda: rounds[0] > 0, timeout=30.0,
                   what="wedged round to complete")
        wait_until(lambda: not tick_firing(),
                   timeout=30.0, interval=0.1,
                   what="staleness alert to resolve")
        assert mon.ready_state()[0] is True
        stop_evt.set()
    finally:
        if ld is not None:
            ld.stop()
        proxy.kill_all()
        mon.close()
        server.close()


# -- the acceptance loop (multi-process, slow) -------------------------


@pytest.mark.slow
def test_continual_loop_end_to_end(tmp_path, mnist_archive):
    """ISSUE 16 acceptance: a 2-replica routed fleet serving an old
    checkpoint; a newer HEALTHY checkpoint lands in the store (plus a
    poisoned newest one) -> the rolling refresh rolls both replicas
    one at a time with ZERO failed requests, serving staleness drops,
    and the diverged blob is never rolled out."""
    from veles.router import (FleetController, RollingRefresh,
                              RouterFrontend)
    from veles.serving import ModelRegistry
    store = tmp_path / "store"
    store.mkdir()
    reg = ModelRegistry(backend="numpy")
    try:
        params = reg.load(
            "mnist", mnist_archive["archive"]).model.params
    finally:
        reg.close()
    t0 = time.time()
    _write_ckpt(store, "m_current-00000001.ckpt.npz.gz", params,
                1.0, t0 - 600, ingest_wall=t0 - 600)
    v1 = str(store / "m_current-00000001.ckpt.npz.gz")
    procs, fronts = [], []
    controller = front = refresher = None
    try:
        for i in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, os.path.join(REPO, "velescli.py"),
                 "serve", "--model",
                 "mnist=%s" % mnist_archive["archive"],
                 "--checkpoint", "mnist=%s" % v1,
                 "--port", "0", "--backend", "numpy", "--no-warmup",
                 "--timeout-ms", "10000"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                env=dict(os.environ, JAX_PLATFORMS="cpu"), text=True))
        replicas = [json.loads(p.stdout.readline())["serving"]
                    for p in procs]
        refresher = RollingRefresh(str(store), "mnist", period_s=0.2,
                                   ready_timeout_s=30.0)
        controller = FleetController(replicas, interval=0.2,
                                     refresher=refresher)
        front = RouterFrontend(controller, port=0)
        x = mnist_archive["x"]
        payload = json.dumps({"model": "mnist",
                              "inputs": [x[0].tolist()],
                              "timeout_ms": 10000}).encode()

        def scraped_walls():
            rows = fleet.scrape_targets(replicas, timeout=5.0)
            return [r.get("metrics", {}).get("serving_ckpt_wall")
                    for r in rows]

        controller.ensure_started()
        wait_until(lambda: all(w == t0 - 600
                               for w in scraped_walls()),
                   what="both replicas serving v1")
        stale_before = max(
            r.get("metrics", {}).get("staleness_seconds") or 0.0
            for r in fleet.scrape_targets(replicas, timeout=5.0))
        assert stale_before >= 500.0
        # continuous client load through the router for the whole roll
        failures, counts, stop = [], [0], threading.Event()

        def hammer():
            while not stop.is_set():
                req = urllib.request.Request(
                    front.url + "/v1/predict", data=payload,
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req,
                                                timeout=15) as resp:
                        json.load(resp)
                    counts[0] += 1
                except Exception as exc:
                    failures.append(repr(exc))

        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()
        # fresh training output lands: a newer HEALTHY checkpoint and
        # an even newer POISONED one
        _write_ckpt(store, "m_current-00000002.ckpt.npz.gz", params,
                    0.5, t0 - 1, ingest_wall=t0 - 2)
        _write_ckpt(store, "m_current-00000003.ckpt.npz.gz", params,
                    99.0, t0, verdict="diverged")
        wait_until(lambda: all(w == t0 - 1 for w in scraped_walls()),
                   timeout=60.0,
                   what="both replicas rolled to v2")
        stop.set()
        for t in threads:
            t.join(timeout=20)
        assert not failures, failures[:3]
        assert counts[0] > 0
        # rolled one at a time, every roll ok, diverged never out
        rolls = refresher.rolls
        assert len(rolls) == 2
        assert all(r["outcome"] == "ok" for r in rolls)
        assert {r["checkpoint"] for r in rolls} == \
            {"m_current-00000002.ckpt.npz.gz"}
        assert {r["replica"] for r in rolls} == set(replicas)
        # staleness dropped end to end
        stale_after = max(
            r.get("metrics", {}).get("staleness_seconds") or 0.0
            for r in fleet.scrape_targets(replicas, timeout=5.0))
        assert stale_after < stale_before - 400.0
        # and the fleet stayed whole
        admitted, total = controller.counts()
        assert (admitted, total) == (2, 2)
    finally:
        if front is not None:
            front.close()
        if controller is not None:
            controller.close()
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
