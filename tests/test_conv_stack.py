"""Conv-stack golden tests (SURVEY.md §4): numpy_run oracle vs traced
XLA path, jax.grad as the second oracle for every hand-written
backward, and the reference's finite-difference numdiff harness."""

import numpy
import pytest

import veles.prng as prng
from veles.accelerated_units import FlowContext, StepCompiler
from veles.backends import XLADevice
from veles.memory import Array
from veles.workflow import Workflow
from veles.znicz_tpu.nn_units import gradient_unit_for
from veles.znicz_tpu.ops.conv import Conv, ConvTanh, ConvRELU
from veles.znicz_tpu.ops.pooling import (
    MaxPooling, MaxAbsPooling, AvgPooling, StochasticPooling)
from veles.znicz_tpu.ops.normalization import LRNormalizerForward
from veles.znicz_tpu.ops.dropout import DropoutForward
from veles.znicz_tpu.ops.cutter import Cutter
from veles.znicz_tpu.ops.deconv import Deconv, Depooling
from veles.znicz_tpu.ops.activation import ForwardTanh, ForwardSinCos

from tests.test_all2all import FeedUnit


def build(fwd_cls, input_shape=(2, 7, 6, 3), gd_kwargs=None,
          **fwd_kwargs):
    prng.seed_all(31)
    wf = Workflow(None, name="wf")
    gen = prng.get("cs")
    x = gen.normal(0, 1.0, input_shape)
    feed = FeedUnit(wf, x)
    fwd = fwd_cls(wf, **fwd_kwargs)
    fwd.link_attrs(feed, ("input", "minibatch_data"))
    fwd.initialize(device=None)
    fwd.numpy_run()
    err = gen.normal(0, 1.0, fwd.output.shape)
    gd_kwargs = dict(gd_kwargs or {})
    gd_kwargs.setdefault("learning_rate", 1.0)
    gd = gradient_unit_for(fwd_cls)(wf, **gd_kwargs)
    gd.setup_forward(fwd)
    gd.err_output = Array(err)
    gd.initialize(device=None)
    comp = StepCompiler([fwd, gd], XLADevice(platform="cpu"))
    return wf, feed, fwd, gd, x, err, comp


def xla_forward(comp, feed, fwd, params, x, train=True):
    import jax

    def fn(p, xv):
        ctx = FlowContext(comp, dict(p), {}, {},
                          jax.random.PRNGKey(7), train)
        ctx.set(feed, "minibatch_data", xv)
        fwd.xla_run(ctx)
        return ctx.get(fwd, "output")

    return jax.jit(fn)(params, x)


def xla_backward(comp, feed, fwd, gd, params, state, x, err,
                 train=True):
    """(err_input, new_params) from the traced gd path."""
    import jax

    def fn(p, s, xv, ev):
        ctx = FlowContext(comp, dict(p), dict(s),
                          {gd.name: gd.hyperparams()},
                          jax.random.PRNGKey(7), train)
        ctx.set(feed, "minibatch_data", xv)
        fwd.xla_run(ctx)
        ctx.set(gd, "err_output", ev)
        gd.xla_run(ctx)
        ei = ctx.values.get((gd.name, "err_input"))
        return ei, ctx.params

    return jax.jit(fn)(params, state, x, err)


def grad_oracle(comp, feed, fwd, params, x, err, train=True):
    """jax.grad of sum(err * forward) wrt (params, x)."""
    import jax
    import jax.numpy as jnp

    def loss(p, xv):
        ctx = FlowContext(comp, dict(p), {}, {},
                          jax.random.PRNGKey(7), train)
        ctx.set(feed, "minibatch_data", xv)
        fwd.xla_run(ctx)
        return jnp.sum(jnp.asarray(err) * ctx.get(fwd, "output"))

    return jax.grad(loss, argnums=(0, 1))(params, x)


FWD_CASES = [
    (Conv, dict(n_kernels=4, kx=3, ky=3)),
    (Conv, dict(n_kernels=4, kx=3, ky=2, sliding=(2, 2), padding=1)),
    (ConvTanh, dict(n_kernels=3, kx=2, ky=2, sliding=(1, 2),
                    padding=(1, 0, 2, 1))),
    (ConvRELU, dict(n_kernels=5, kx=3, ky=3, padding=2, sliding=3)),
    (MaxPooling, dict(kx=2, ky=2)),
    (MaxPooling, dict(kx=3, ky=2, sliding=(2, 3))),
    (MaxAbsPooling, dict(kx=2, ky=2)),
    (AvgPooling, dict(kx=2, ky=2)),
    (AvgPooling, dict(kx=3, ky=3, sliding=2)),
    (LRNormalizerForward, dict()),
    (LRNormalizerForward, dict(n=4, alpha=0.01, beta=0.5, k=1.0)),
    (Cutter, dict(padding=(1, 1, 2, 1))),
    (Deconv, dict(n_kernels=3, kx=2, ky=2, sliding=2)),
    (Depooling, dict(kx=2, ky=2)),
    (ForwardTanh, dict()),
    (ForwardSinCos, dict()),
    (DropoutForward, dict(dropout_ratio=0.0)),
]


@pytest.mark.parametrize("cls,kwargs", FWD_CASES,
                         ids=lambda v: getattr(v, "__name__", str(v)))
def test_forward_parity(cls, kwargs):
    wf, feed, fwd, gd, x, err, comp = build(cls, **{"gd_kwargs": {}},
                                            **kwargs)
    golden = numpy.array(fwd.output.mem)
    y = xla_forward(comp, feed, fwd, comp.gather_params(), x)
    assert numpy.allclose(numpy.asarray(y), golden, atol=3e-5), \
        numpy.abs(numpy.asarray(y) - golden).max()


@pytest.mark.parametrize("cls,kwargs", FWD_CASES,
                         ids=lambda v: getattr(v, "__name__", str(v)))
def test_backward_vs_jax_grad_and_numpy(cls, kwargs):
    import jax
    wf, feed, fwd, gd, x, err, comp = build(cls, **{"gd_kwargs": {}},
                                            **kwargs)
    params0 = comp.gather_params()
    state0 = comp.gather_state()
    # numpy backward
    gd.numpy_run()
    ei_np = numpy.array(gd.err_input.mem) if gd.need_err_input else None
    # traced backward
    ei_x, params1 = xla_backward(comp, feed, fwd, gd, params0, state0,
                                 x, err)
    # jax.grad oracle
    gp, gx = grad_oracle(comp, feed, fwd, params0, x, err)

    assert numpy.allclose(ei_np, numpy.asarray(gx), atol=2e-4), \
        numpy.abs(ei_np - numpy.asarray(gx)).max()
    assert numpy.allclose(ei_np, numpy.asarray(ei_x), atol=2e-4)
    if fwd.PARAMS and fwd.weights:
        grad_w_oracle = numpy.asarray(gp[fwd.name]["weights"])
        # lr=1, moment=0: w1 = w0 - grad
        grad_w_np = numpy.array(params0[fwd.name]["weights"]) \
            - fwd.weights.map_read().mem
        grad_w_x = numpy.array(params0[fwd.name]["weights"]) \
            - numpy.asarray(params1[fwd.name]["weights"])
        assert numpy.allclose(grad_w_np, grad_w_oracle, atol=3e-4), \
            numpy.abs(grad_w_np - grad_w_oracle).max()
        assert numpy.allclose(grad_w_x, grad_w_oracle, atol=3e-4)


def test_numdiff_conv():
    """Reference gd_numdiff pattern: central finite differences on the
    numpy oracle confirm the analytic err_input (SURVEY.md §4
    "Gradient checks")."""
    wf, feed, fwd, gd, x, err, comp = build(
        Conv, input_shape=(1, 5, 5, 2),
        gd_kwargs={"learning_rate": 0.0},  # keep weights fixed for FD
        **dict(n_kernels=2, kx=3, ky=3))
    gd.numpy_run()
    analytic = numpy.array(gd.err_input.mem)
    x = x.copy()  # Array(x) aliases x's buffer; keep a pristine copy
    h = 1e-3
    rng = numpy.random.Generator(numpy.random.PCG64(3))
    flat_idx = rng.choice(x.size, size=20, replace=False)
    for fi in flat_idx:
        idx = numpy.unravel_index(fi, x.shape)
        for sign, store in ((+1, "plus"), (-1, "minus")):
            feed.minibatch_data.map_write()
            feed.minibatch_data.mem[...] = x
            feed.minibatch_data.mem[idx] += sign * h
            fwd.numpy_run()
            val = float((err * fwd.output.mem).sum())
            if sign > 0:
                lp = val
            else:
                lm = val
        numeric = (lp - lm) / (2 * h)
        assert abs(numeric - analytic[idx]) < 5e-2, (idx, numeric,
                                                     analytic[idx])


def test_dropout_statistics():
    """Nonzero ratio: eval is identity; train keeps ~keep fraction and
    preserves the mean (inverted scaling) on both backends."""
    import jax
    wf, feed, fwd, gd, x, err, comp = build(
        DropoutForward, input_shape=(64, 4, 4, 8),
        **dict(dropout_ratio=0.4))
    # numpy train path
    fwd.numpy_run()
    kept = (fwd.output.mem != 0).mean()
    assert abs(kept - 0.6) < 0.05
    # traced eval path = identity
    y_eval = xla_forward(comp, feed, fwd, comp.gather_params(), x,
                         train=False)
    assert numpy.allclose(numpy.asarray(y_eval), x, atol=1e-6)
    # traced train path: same keep-rate ballpark
    y_train = numpy.asarray(
        xla_forward(comp, feed, fwd, comp.gather_params(), x,
                    train=True))
    assert abs((y_train != 0).mean() - 0.6) < 0.05


def test_stochastic_pooling_modes():
    wf, feed, fwd, gd, x, err, comp = build(
        StochasticPooling, input_shape=(3, 6, 6, 4),
        **dict(kx=2, ky=2))
    golden_train = numpy.array(fwd.output.mem)  # numpy train sample
    # every sampled value comes from its window
    assert golden_train.shape == (3, 3, 3, 4)
    # eval mode: deterministic prob-weighted average, backends agree
    fwd2 = fwd
    y_eval = xla_forward(comp, feed, fwd2, comp.gather_params(), x,
                         train=False)
    patches = fwd._padded_patches(numpy, x.astype(numpy.float32), 0.0)
    probs = fwd._probs(numpy, patches)
    expected = (patches * probs).sum(axis=3)
    assert numpy.allclose(numpy.asarray(y_eval), expected, atol=3e-5)
    # traced train backward routes err through recorded offsets
    ei_x, _ = xla_backward(comp, feed, fwd, gd, comp.gather_params(),
                           comp.gather_state(), x, err)
    assert numpy.asarray(ei_x).shape == x.shape


def test_max_pooling_tie_routing_parity():
    """The traced reduce_window/select-and-scatter fast path must
    route TIES exactly like the oracle's argmax-first-wins winner
    offsets: quantized input forces many equal values per window, and
    err_input must match element-for-element (the continuous-data
    parametrized cases above essentially never tie)."""
    wf, feed, fwd, gd, x, err, comp = build(
        MaxPooling, input_shape=(4, 9, 9, 3), gd_kwargs={},
        kx=3, ky=3, sliding=2)
    gen = prng.get("tie")
    xq = (gen.randint(0, 3, x.shape) * 0.5).astype(numpy.float32)
    fwd.input.map_write()
    fwd.input.mem[...] = xq
    fwd.numpy_run()
    errq = gen.normal(0, 1.0, fwd.output.shape) \
        .astype(numpy.float32)
    gd.err_output.map_write()
    gd.err_output.mem[...] = errq
    gd.numpy_run()
    ei_oracle = numpy.array(gd.err_input.mem)

    params = comp.gather_params()
    state = comp.gather_state()
    y_x = xla_forward(comp, feed, fwd, params, xq)
    assert numpy.array_equal(numpy.asarray(y_x), fwd.output.mem)
    ei_x, _ = xla_backward(comp, feed, fwd, gd, params, state, xq,
                           errq)
    ei_x = numpy.asarray(ei_x)
    # the ROUTING must be identical (which cells receive gradient);
    # cells fed by several overlapping windows may differ by summation
    # order, so values compare to float tolerance
    assert numpy.array_equal(ei_oracle == 0.0, ei_x == 0.0), \
        "tie routing differs between select-and-scatter and the " \
        "winner-offset oracle"
    assert numpy.allclose(ei_oracle, ei_x, atol=1e-5)
