"""The reactor core (ISSUE 9): loop mechanics, incremental frame
assembly, slow-reader backpressure, the N=8 echo micro-bench against
the thread-per-connection baseline, inline probe serving, and the
master:reactor readiness/status surfaces.

The chaos suite (tests/test_chaos.py) is the regression harness for
the PORT itself — fencing, reconnect-through-kill, trace propagation
and 2-slave convergence under none/int8/topk all run over the reactor
now, unchanged.
"""

import socket
import socketserver
import struct
import threading
import time

import pytest

from veles import reactor
from veles.server import (MasterServer, framed_server, recv_frame,
                          send_frame)
from tests.test_service import make_wf


@pytest.fixture(autouse=True)
def _mnist_config_guard():
    """make_wf (tests/test_service.py) mutates root.mnist without
    restoring; tests here must not leak that config into later files
    (the same guard idiom as tests/test_health.py)."""
    from veles.config import root
    # the sample's module-level defaults must be in root BEFORE the
    # snapshot, or a never-touched key restores as an explicit None
    from veles.znicz_tpu.models import mnist  # noqa: F401
    saved_loader = {k: root.mnist.loader.get(k)
                    for k in ("minibatch_size", "n_train", "n_valid")}
    saved_epochs = root.mnist.decision.get("max_epochs")
    yield
    root.mnist.loader.update(saved_loader)
    root.mnist.decision.max_epochs = saved_epochs


def _drain(sock):
    try:
        sock.close()
    except OSError:
        pass


# -- loop mechanics ----------------------------------------------------


def test_call_soon_crosses_threads_and_timers_fire_in_order():
    loop = reactor.get_reactor()
    seen = []
    done = threading.Event()
    loop.call_soon(seen.append, "soon")
    loop.call_later(0.02, seen.append, "later-20ms")
    loop.call_later(0.001, seen.append, "later-1ms")
    loop.call_later(0.05, lambda: (seen.append("last"), done.set()))
    assert done.wait(5.0), seen
    assert seen == ["soon", "later-1ms", "later-20ms", "last"]
    assert not loop.in_loop()           # we are the test thread


def test_every_rearms_until_cancelled():
    loop = reactor.get_reactor()
    hits = []
    timer = loop.every(0.01, lambda: hits.append(1))
    deadline = time.monotonic() + 5.0
    while len(hits) < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(hits) >= 3
    timer.cancel()
    time.sleep(0.05)
    frozen = len(hits)
    time.sleep(0.1)
    assert len(hits) <= frozen + 1      # at most one in-flight firing


def test_loop_lag_gauge_updates():
    loop = reactor.get_reactor()
    from veles import telemetry
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        fams = {f.name for f in telemetry.get_registry().families()}
        if "veles_reactor_loop_lag_seconds" in fams:
            break
        time.sleep(0.05)
    assert "veles_reactor_loop_lag_seconds" in fams
    # a healthy idle loop lags microseconds, never seconds
    assert loop.loop_lag_s < 1.0


# -- framed assembly over the reactor ----------------------------------


def _echo_server():
    done = threading.Event()
    server = framed_server(("127.0.0.1", 0), lambda req: req, done,
                           lambda sid, clean=False: None)
    return server


def test_framed_echo_assembles_fragmented_frames():
    """A frame dripped one byte at a time (header, tag and payload
    all fragmented) must assemble incrementally and echo back whole —
    the blocking-recv-loop behavior, reproduced by the state
    machine."""
    server = _echo_server()
    try:
        sock = socket.create_connection(server.server_address,
                                        timeout=10)
        payload = ("echo", 42, b"z" * 257)
        import hashlib
        import hmac as hmac_mod
        import pickle
        from veles.server import _secret
        blob = pickle.dumps(payload, protocol=5)
        tag = hmac_mod.new(_secret(), blob, hashlib.sha256).digest()
        frame = struct.pack(">I", len(blob)) + tag + blob
        for i in range(0, len(frame), 7):      # 7-byte drip
            sock.sendall(frame[i:i + 7])
            if i < 64:
                time.sleep(0.001)              # force tiny reads
        assert recv_frame(sock) == payload
        # a second, normally-sent frame still works on the same
        # connection (no leftover assembly state)
        send_frame(sock, ("echo", 2))
        assert recv_frame(sock) == ("echo", 2)
        _drain(sock)
    finally:
        server.server_close()


def test_framed_rejects_tampered_hmac_and_oversized_header():
    server = _echo_server()
    try:
        # tampered byte -> the server refuses to deserialize and
        # severs the connection
        sock = socket.create_connection(server.server_address,
                                        timeout=10)
        import hashlib
        import hmac as hmac_mod
        import pickle
        from veles.server import _secret
        blob = pickle.dumps(("echo", 1), protocol=5)
        tag = hmac_mod.new(_secret(), blob, hashlib.sha256).digest()
        bad = bytearray(blob)
        bad[-1] ^= 1
        sock.sendall(struct.pack(">I", len(bad)) + tag + bytes(bad))
        assert recv_frame(sock) is None        # server hung up
        _drain(sock)

        # oversized length header -> dropped before any allocation
        sock = socket.create_connection(server.server_address,
                                        timeout=10)
        sock.sendall(struct.pack(">I", (1 << 30) + 1) + b"\0" * 32)
        assert recv_frame(sock) is None
        _drain(sock)

        # and the server is still alive for a healthy peer
        sock = socket.create_connection(server.server_address,
                                        timeout=10)
        send_frame(sock, ("echo", 3))
        assert recv_frame(sock) == ("echo", 3)
        _drain(sock)
    finally:
        server.server_close()


# -- slow-reader backpressure (ISSUE 9 satellite) ----------------------


def test_slow_reader_drops_at_write_queue_cap():
    """A stalled slave connection accumulates a BOUNDED reply queue
    and is dropped at the cap with a counted fault
    (``backpressure_drops``); its lease revokes, its jobs requeue,
    and a healthy slave then finishes the run — the stall never
    blocks the merge path."""
    from veles.client import SlaveClient
    wf = make_wf("BackpressureMaster", max_epochs=None)
    wf.decision.max_epochs = 2
    server = MasterServer(wf, "127.0.0.1:0", max_epochs=2,
                          slave_timeout=30.0,
                          max_write_buffer=1 << 16)
    server.start_background()

    # shrink BOTH kernel buffers (client receive before connect —
    # loopback autotune can otherwise swallow megabytes of replies
    # in flight and starve the server-side queue of growth)
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 8192)
    sock.settimeout(10)
    sock.connect(server.bound_address)
    send_frame(sock, ("hello", "stall", "none"))
    _, sid, lease = recv_frame(sock)[:3]
    # ... and the server side's send buffer, so queued replies land
    # in the reactor's write queue, not the kernel's
    deadline = time.time() + 10
    conn = None
    while time.time() < deadline and conn is None:
        for c in server._server.connections():
            if c.slave_id == sid:
                conn = c
        time.sleep(0.01)
    assert conn is not None
    conn.sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 8192)

    # flood job requests and NEVER read a reply: each response is a
    # weight-carrying payload, so the reply queue must hit the cap
    deadline = time.time() + 30
    while time.time() < deadline \
            and server.faults["backpressure_drops"] < 1:
        try:
            send_frame(sock, ("job", sid, lease))
        except OSError:
            break                       # server dropped us: done
        time.sleep(0.002)
    deadline = time.time() + 10
    while time.time() < deadline \
            and server.faults["backpressure_drops"] < 1:
        time.sleep(0.02)
    st = server.status()
    assert st["faults"]["backpressure_drops"] >= 1, st
    assert st["faults"]["drops"] >= 1, st       # lease revoked too
    assert str(sid) not in st["slaves"], st
    _drain(sock)

    # the merge path was never blocked: a healthy slave completes
    healthy = make_wf("BackpressureHealthy")
    healthy.is_slave = True
    SlaveClient(healthy, "127.0.0.1:%d" % server.bound_address[1],
                name="healthy", io_timeout=10.0).run_forever()
    assert server.done.is_set()


def test_status_reports_per_slave_write_queue_depth():
    wf = make_wf("DepthMaster", max_epochs=None)
    wf.decision.max_epochs = 2
    server = MasterServer(wf, "127.0.0.1:0", max_epochs=2)
    server.start_background()
    try:
        sock = socket.create_connection(server.bound_address,
                                        timeout=10)
        send_frame(sock, ("hello", "depth", "none"))
        _, sid, _lease = recv_frame(sock)[:3]
        row = server.status()["slaves"][str(sid)]
        # a healthy, fully-drained connection queues nothing
        assert row["write_queue_bytes"] == 0
        _drain(sock)
    finally:
        server.kill()


# -- acceptance: N=8 echo micro-bench ----------------------------------


def _run_echo_clients(port, n=8, duration=0.5, payload=b"x" * 512):
    counts = [0] * n
    stop = time.perf_counter() + duration
    errors = []

    def client(i):
        try:
            s = socket.create_connection(("127.0.0.1", port),
                                         timeout=10)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            frame = ("echo", i, payload)
            while time.perf_counter() < stop:
                send_frame(s, frame)
                if recv_frame(s)[0] != "echo":
                    raise AssertionError("bad echo")
                counts[i] += 1
            _drain(s)
        except Exception as exc:
            errors.append(exc)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    return sum(counts) / duration


def _threaded_echo_baseline():
    """The pre-ISSUE-9 shape: one blocking thread per connection."""

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            self.request.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
            try:
                while True:
                    req = recv_frame(self.request)
                    if req is None:
                        break
                    send_frame(self.request, req)
            except (ConnectionError, OSError):
                pass

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    return Server(("127.0.0.1", 0), Handler)


def test_echo_reactor_at_least_threaded_throughput_8_conns():
    """Acceptance (ISSUE 9): with 8 concurrent connections hammering
    framed echo round-trips, the single-threaded reactor must be no
    slower than the thread-per-connection baseline (measured ~3x
    faster here — no GIL-contended thread wakeup per frame). Retried
    to keep CI scheduling noise from flaking an honest >= bound."""
    last = None
    for _ in range(3):
        baseline = _threaded_echo_baseline()
        threading.Thread(target=baseline.serve_forever,
                         daemon=True).start()
        threaded = _run_echo_clients(baseline.server_address[1])
        baseline.shutdown()
        baseline.server_close()

        server = _echo_server()
        try:
            looped = _run_echo_clients(server.server_address[1])
        finally:
            server.server_close()
        last = (looped, threaded)
        if looped >= threaded:
            return
    pytest.fail("reactor echo slower than threaded baseline across "
                "3 attempts: reactor %.0f rt/s vs threaded %.0f rt/s"
                % last)


# -- HTTP plane on the loop --------------------------------------------


def test_probes_answer_inline_without_thread_per_request():
    """/healthz and /metrics on web-status are served ON the loop:
    50 sequential probe requests spawn zero worker threads (only the
    provider-pulling routes defer)."""
    import urllib.request
    from veles.web_status import WebStatus
    status = WebStatus(port=0)
    try:
        base = "http://127.0.0.1:%d" % status.port
        urllib.request.urlopen(base + "/healthz", timeout=10).read()
        before = threading.active_count()
        for _ in range(50):
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=10) as resp:
                assert resp.status == 200
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as resp:
                assert resp.status == 200
        assert threading.active_count() <= before + 1
        # the deferred route still works (worker-thread handoff)
        with urllib.request.urlopen(base + "/status.json",
                                    timeout=10) as resp:
            assert resp.status == 200
    finally:
        status.close()


def test_fleet_scrape_reports_reactor_lag():
    """velescli top's scraper surfaces the per-target reactor loop
    lag once the lag probe has ticked into the registry."""
    from veles.fleet import scrape_target
    from veles.web_status import WebStatus
    status = WebStatus(port=0)
    try:
        deadline = time.monotonic() + 5.0
        row = {}
        while time.monotonic() < deadline:
            row = scrape_target("http://127.0.0.1:%d" % status.port,
                                timeout=10)
            if "reactor_lag_s" in row.get("metrics", {}):
                break
            time.sleep(0.1)
        assert "reactor_lag_s" in row["metrics"], row
        assert row["metrics"]["reactor_lag_s"] < 1.0
    finally:
        status.close()


def test_current_lag_observes_a_wedged_loop():
    """loop_lag_s is the loop's SELF-measurement — a wedged loop
    freezes it near zero. current_lag() must instead grow while the
    loop is parked behind a blocking callback (what the
    master:reactor readiness check reads)."""
    loop = reactor.get_reactor()
    started = threading.Event()
    release = threading.Event()

    def wedge():
        started.set()
        release.wait(5.0)           # deliberately blocks the loop

    loop.call_soon(wedge)
    assert started.wait(5.0)
    time.sleep(0.8)                 # probe now overdue by ~0.5s
    try:
        assert loop.current_lag() > 0.3, loop.current_lag()
    finally:
        release.set()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and loop.current_lag() > 0.3:
        time.sleep(0.05)
    assert loop.current_lag() < 0.3     # recovered


def test_accept_factory_failure_keeps_listener_alive(monkeypatch):
    """One failing connection construction must cost THAT connection
    only — never tear down the acceptor (which would silently stop
    the listener forever while `accepting` stayed True)."""
    server = _echo_server()
    try:
        boom = {"n": 1}
        real = server.build_connection

        def flaky(sock, addr):
            if boom["n"]:
                boom["n"] -= 1
                raise RuntimeError("transient factory failure")
            return real(sock, addr)

        monkeypatch.setattr(server, "build_connection", flaky)
        victim = socket.create_connection(server.server_address,
                                          timeout=10)
        # the victim's connection dies...
        assert recv_frame(victim) is None
        _drain(victim)
        # ...but the listener survives and still accepts
        sock = socket.create_connection(server.server_address,
                                        timeout=10)
        send_frame(sock, ("echo", 1))
        assert recv_frame(sock) == ("echo", 1)
        assert server.accepting
        _drain(sock)
    finally:
        server.server_close()


def test_http_bad_content_length_answers_400():
    """A garbled or negative Content-Length must answer 400 like the
    old threaded frontend did, not drop the connection replyless."""
    from veles.web_status import WebStatus
    status = WebStatus(port=0)
    try:
        for value in ("abc", "-5"):
            sock = socket.create_connection(("127.0.0.1",
                                             status.port), timeout=10)
            sock.sendall(("POST /update HTTP/1.1\r\n"
                          "Host: x\r\nContent-Length: %s\r\n\r\n"
                          % value).encode())
            reply = sock.recv(4096)
            assert reply.startswith(b"HTTP/1.1 400"), (value, reply)
            _drain(sock)
    finally:
        status.close()


def test_http_connections_untracked_without_a_request():
    """TCP-only health checks (open, close, no HTTP request) must not
    accumulate connection objects in the server's tracking set."""
    from veles.web_status import WebStatus
    status = WebStatus(port=0)
    try:
        for _ in range(20):
            sock = socket.create_connection(("127.0.0.1",
                                             status.port), timeout=10)
            sock.close()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline \
                and status._server.connections():
            time.sleep(0.05)
        assert status._server.connections() == []
    finally:
        status.close()


def test_fenced_ping_severs_so_zombie_heartbeat_counts_once():
    """The send-only heartbeat cannot read the ("stale",) a fenced
    ping earns, so the server severs the connection after the reply
    drains — a zombie slave deep in a long compute stops beating at
    the first fence instead of inflating stale_pings once per
    ping_interval until its next round-trip."""
    wf = make_wf("StalePingMaster", max_epochs=None)
    wf.decision.max_epochs = 50
    server = MasterServer(wf, "127.0.0.1:0", max_epochs=50)
    server.start_background()
    try:
        sock = socket.create_connection(server.bound_address,
                                        timeout=10)
        send_frame(sock, ("hello", "zombie", "none"))
        _, sid, lease = recv_frame(sock)[:3]
        server.drop_slave(sid)          # revoke out from under it
        send_frame(sock, ("ping", sid, lease))
        assert recv_frame(sock) == ("stale",)
        # the connection is severed after the fence: further beats
        # die at the socket, not at the fault counters
        assert recv_frame(sock) is None
        assert server.faults["stale_pings"] == 1
        _drain(sock)
    finally:
        server.kill()


# -- master:reactor readiness ------------------------------------------


def test_master_reactor_readiness_check():
    from veles import health
    from veles.health import HealthMonitor
    wf = make_wf("ReactorReadyMaster", max_epochs=None)
    wf.decision.max_epochs = 50
    server = MasterServer(wf, "127.0.0.1:0", max_epochs=50)
    server.start_background()
    try:
        with health.scoped(HealthMonitor(interval=30.0)) as mon:
            server.register_health(mon)
            ok, reasons = mon.ready_state()
            assert ok is True, reasons
            doc = mon.probe("/readyz")[1]
            assert doc["checks"]["master:reactor"]["ok"] is True
            # an impossible lag threshold flips the check with a
            # reason naming the lag
            server.reactor_lag_ready_s = -1.0
            mon.tick()
            ok, reasons = mon.ready_state()
            assert ok is False
            assert any("reactor loop lag" in r for r in reasons), \
                reasons
    finally:
        server.kill()
