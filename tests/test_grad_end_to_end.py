"""End-to-end gradient oracle: jax.grad of the ENTIRE composed forward
(+ loss) must match the hand-written GD chain's effective gradients on
every parameter of every unit — the strongest form of SURVEY.md §4's
"jax.grad as a second oracle", applied to whole models rather than
single units. Catches chain-composition mistakes (mis-linked
err routing, missing residual terms) that per-unit checks cannot."""

import numpy
import pytest

import veles.prng as prng
from veles.config import root


def _effective_grads(wf, lr=1e-3):
    """Run ONE compiled train step with lr=lr, momentum/decay 0 on a
    fixed minibatch; -> (batch, param-grads as (w_before−w_after)/lr)."""
    import jax
    from veles.loader.base import CLASS_TRAIN
    step = wf.xla_step
    for gd in wf.gds:
        if gd is not None:
            gd.learning_rate = lr
            gd.learning_rate_bias = lr
            gd.gradient_moment = 0.0
            gd.gradient_moment_bias = 0.0
            gd.weights_decay = 0.0
            gd.weights_decay_bias = 0.0
    loader = wf.loader
    # scan/stream modes skip host minibatch fills (device_gather);
    # this harness feeds the compiled PER-STEP function from the host
    # arrays, so force real fills — and prove the batch isn't the
    # stale zeros a silent mis-setup would produce
    loader.device_gather = False
    loader.run()
    while loader.minibatch_class != CLASS_TRAIN:
        loader.run()
    batch = step._gather_batch()
    assert numpy.asarray(batch["data"]).any(), "zero batch: harness bug"
    fn = step.compiler.compile(step._batch_spec, train=True)
    import jax.numpy as jnp
    copy = (lambda t: jax.tree_util.tree_map(jnp.copy, t))
    before = copy(step.params)
    params2, _, _ = fn(copy(step.params), copy(step.state), batch,
                       step._gather_hyper(), jax.random.PRNGKey(7))
    grads = jax.tree_util.tree_map(
        lambda a, b: (numpy.asarray(a) - numpy.asarray(b)) / lr,
        before, params2)
    return batch, before, grads


def _autodiff_grads(wf, batch, params):
    """jax.grad of the pure composed forward+loss over the same
    minibatch."""
    import jax
    from veles.accelerated_units import FlowContext
    step = wf.xla_step
    comp = step.compiler
    loader = wf.loader

    def loss_fn(p):
        ctx = FlowContext(comp, dict(p), {}, step._gather_hyper(),
                          jax.random.PRNGKey(7), True)
        for name, (unit, attr) in step._batch_spec.items():
            ctx.set(unit, attr, batch[name])
        for u in step.eval_units:
            u.xla_run(ctx)
        return ctx.outputs["loss"]

    return jax.grad(loss_fn)(params)


def _assert_grads_match(wf, atol=2e-3):
    batch, params, got = _effective_grads(wf)
    want = _autodiff_grads(wf, batch, params)
    for uname, sub in want.items():
        for pname, g_ref in sub.items():
            g_ref = numpy.asarray(g_ref)
            g_hat = numpy.asarray(got[uname][pname])
            scale = max(numpy.abs(g_ref).max(), 1e-3)
            assert numpy.allclose(g_hat, g_ref, atol=atol * scale), \
                "%s.%s: max |Δ| %.3g vs scale %.3g" % (
                    uname, pname,
                    numpy.abs(g_hat - g_ref).max(), scale)


def test_transformer_lm_grads_match_autodiff():
    """Embedding + attention + layernorm + FFN + token_dense +
    EvaluatorLM, composed: handwritten chain == jax.grad."""
    prng.seed_all(4242)
    from veles.znicz_tpu.models import transformer_lm
    saved = root.lm.loader.to_dict()
    root.lm.loader.update({"minibatch_size": 8, "n_train": 32,
                           "n_valid": 16, "seq_len": 12})
    saved_model = root.lm.model.to_dict()
    root.lm.model.update({"dim": 16, "heads": 4, "layers": 2,
                          "ffn_hidden": 32})
    try:
        wf = transformer_lm.create_workflow(name="GradLM")
        wf.initialize(device="cpu")
        _assert_grads_match(wf)
    finally:
        root.lm.loader.update(saved)
        root.lm.model.update(saved_model)


def test_blocked_attention_lm_grads_match_autodiff():
    """Same model through the flash-style blocked attention path."""
    prng.seed_all(4242)
    from veles.znicz_tpu.models import transformer_lm
    saved = root.lm.loader.to_dict()
    root.lm.loader.update({"minibatch_size": 8, "n_train": 32,
                           "n_valid": 16, "seq_len": 12})
    saved_model = root.lm.model.to_dict()
    root.lm.model.update({"dim": 16, "heads": 4, "layers": 1,
                          "ffn_hidden": 32, "attn_block": 4})
    try:
        wf = transformer_lm.create_workflow(name="GradLMBlk")
        wf.initialize(device="cpu")
        _assert_grads_match(wf)
    finally:
        root.lm.loader.update(saved)
        root.lm.model.update(saved_model)


def test_ring_attention_lm_grads_match_autodiff():
    """Same model through the sequence-parallel ppermute ring on the
    virtual mesh: jax.grad differentiates THROUGH shard_map, so this
    proves the hand-written ring backward end to end."""
    prng.seed_all(4242)
    from veles.znicz_tpu.models import transformer_lm
    saved = root.lm.loader.to_dict()
    saved_parallel = root.lm.parallel.to_dict()
    root.lm.loader.update({"minibatch_size": 8, "n_train": 32,
                           "n_valid": 16, "seq_len": 12})
    saved_model = root.lm.model.to_dict()
    root.lm.model.update({"dim": 16, "heads": 4, "layers": 1,
                          "ffn_hidden": 32})
    root.lm.parallel.update({"seq": 4, "model": 1, "data": 1})
    try:
        wf = transformer_lm.create_workflow(name="GradLMRing")
        wf.initialize(device="cpu")
        from veles.znicz_tpu.ops.attention import MultiHeadAttention
        assert any(f.seq_mesh is not None for f in wf.forwards
                   if isinstance(f, MultiHeadAttention))
        _assert_grads_match(wf)
    finally:
        root.lm.loader.update(saved)
        root.lm.model.update(saved_model)
        root.lm.parallel.update(saved_parallel)


def test_conv_stack_grads_match_autodiff():
    """The CIFAR conv/pool/dense/softmax-CE chain == jax.grad."""
    prng.seed_all(1717)
    from veles.znicz_tpu.models import cifar10
    saved = {k: root.cifar.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    root.cifar.loader.update({"n_train": 64, "n_valid": 32,
                              "minibatch_size": 16})
    try:
        wf = cifar10.create_workflow(name="GradCifar")
        wf.initialize(device="cpu")
        _assert_grads_match(wf, atol=5e-3)
    finally:
        root.cifar.loader.update(saved)


def test_alexnet_grads_match_autodiff():
    """The FULL AlexNet stack — conv(s4) + LRN + overlapping pools +
    dropout(0: deterministic identity mask, traced RNG path still
    exercised) + FC — == jax.grad, through the strided im2col weight-
    grad path too."""
    prng.seed_all(2929)
    from veles.znicz_tpu.models import imagenet
    from veles.znicz_tpu.standard_workflow import StandardWorkflow
    saved = imagenet.root.imagenet.loader.to_dict()
    root.imagenet.loader.update({
        "minibatch_size": 8, "n_train": 32, "n_valid": 16,
        "n_classes": 4, "scale": (75, 75), "crop": (67, 67)})
    layers = imagenet.alexnet_layers(4)
    for layer in layers:
        if layer["type"] == "dropout":
            layer["->"]["dropout_ratio"] = 0.0
    try:
        wf = StandardWorkflow(
            None, name="GradAlex", layers=layers,
            loader_factory=imagenet.make_loader,
            decision_config={"max_epochs": 1})
        wf.initialize(device="cpu")
        _assert_grads_match(wf, atol=5e-3)
    finally:
        root.imagenet.loader.update(saved)
