"""Tests for config tree, mutable Bools, unit graph, and workflow driver
(SURVEY.md §2.1 components; mechanisms per §4)."""

import io

import numpy
import pytest

from veles.config import Config, Tune, root
from veles.mutable import Bool, LinkableAttribute
from veles.units import Unit, TrivialUnit
from veles.workflow import Workflow
from veles import prng


# -- config ----------------------------------------------------------------

def test_config_autovivify_and_update():
    cfg = Config("test")
    cfg.a.b.c = 3
    assert cfg.a.b.c == 3
    cfg.update({"a": {"b": {"d": 4}, "e": "x"}})
    assert cfg.a.b.c == 3 and cfg.a.b.d == 4 and cfg.a.e == "x"
    assert cfg.flatten() == {"a.b.c": 3, "a.b.d": 4, "a.e": "x"}


def test_config_override_literals_and_strings():
    cfg = Config("root")
    cfg.apply_override("root.x.y=10")
    cfg.apply_override("x.z=[1, 2]")
    cfg.apply_override("x.name=hello world")
    assert cfg.x.y == 10
    assert cfg.x.z == [1, 2]
    assert cfg.x.name == "hello world"
    with pytest.raises(ValueError):
        cfg.apply_override("nonsense")


def test_tune_resolution_and_collection():
    cfg = Config("test")
    cfg.lr = Tune(0.01, 0.0001, 0.1)
    cfg.layers.n = Tune(2, 1, 5)
    assert cfg.lr == 0.01            # reads resolve to default
    assert cfg.layers.n == 2
    tunables = cfg.tunables()
    assert set(tunables) == {"lr", "layers.n"}
    assert tunables["layers.n"].discrete
    assert tunables["lr"].clip(5.0) == 0.1


def test_root_common_defaults_exist():
    assert root.common.engine.backend in ("xla", "numpy")
    assert isinstance(root.common.dirs.cache, str)


# -- mutable ---------------------------------------------------------------

def test_bool_algebra_is_live():
    a, b = Bool(False), Bool(True)
    both = a & b
    either = a | b
    neither = ~(a | b)
    assert not both and either and not neither
    a << True
    assert both
    b << False
    a << False
    assert neither
    with pytest.raises(ValueError):
        both << True  # derived bools are read-only


def test_linkable_attribute_aliases_and_breaks_on_write():
    class Src:
        pass

    class Dst:
        pass

    src, dst = Src(), Dst()
    src.output = 42
    LinkableAttribute.install(dst, "input", src, "output")
    assert dst.input == 42
    src.output = 43
    assert dst.input == 43
    dst.input = 7          # one-way link: write breaks the alias
    assert dst.input == 7 and src.output == 43


# -- unit graph ------------------------------------------------------------

class Recorder(Unit):
    log_list = None

    def run(self):
        self.log_list.append(self.name)


def _make_chain(wf, names, log):
    units = []
    prev = wf.start_point
    for name in names:
        u = Recorder(wf, name=name)
        u.log_list = log
        u.link_from(prev)
        prev = u
        units.append(u)
    wf.end_point.link_from(prev)
    return units


def test_linear_workflow_runs_in_order():
    wf = Workflow(name="wf")
    log = []
    _make_chain(wf, ["a", "b", "c"], log)
    wf.initialize()
    wf.run()
    assert log == ["a", "b", "c"]
    assert wf.end_point.reached


def test_gate_skip_propagates_gate_block_stops():
    wf = Workflow(name="wf")
    log = []
    a, b, c = _make_chain(wf, ["a", "b", "c"], log)
    b.gate_skip << True
    wf.initialize()
    wf.run()
    assert log == ["a", "c"]          # b skipped but propagated
    log.clear()
    b.gate_skip << False
    b.gate_block << True
    wf.run()
    assert log == ["a"]               # blocked: nothing downstream
    assert not wf.end_point.reached


def test_cycle_runs_until_gate_opens():
    """The training-loop shape: a repeater-headed cycle gated into the
    end point (SURVEY.md §1: loader → ... → gd → repeater → loader until
    decision.complete)."""
    from veles.units import Repeater

    wf = Workflow(name="loop")
    done = Bool(False)

    class Counter(Unit):
        count = 0

        def run(self):
            self.count += 1
            if self.count >= 5:
                done << True

    rep = Repeater(wf, name="repeater")
    c = Counter(wf, name="counter")
    rep.link_from(wf.start_point)
    c.link_from(rep)
    rep.link_from(c)                  # the back edge closing the cycle
    wf.end_point.link_from(c)
    wf.end_point.gate_block = ~done
    wf.initialize()
    wf.run()
    assert c.count == 5
    assert wf.end_point.reached


def test_fan_in_waits_for_all_open_links():
    wf = Workflow(name="fanin")
    log = []
    a = Recorder(wf, name="a")
    b = Recorder(wf, name="b")
    c = Recorder(wf, name="c")
    for u in (a, b, c):
        u.log_list = log
    a.link_from(wf.start_point)
    b.link_from(wf.start_point)
    c.link_from(a, b)
    wf.end_point.link_from(c)
    wf.initialize()
    wf.run()
    assert log.index("c") > log.index("a")
    assert log.index("c") > log.index("b")
    assert log.count("c") == 1


def test_graph_dump_and_stats():
    wf = Workflow(name="wf")
    log = []
    _make_chain(wf, ["a", "b"], log)
    wf.initialize()
    wf.run()
    dot = wf.generate_graph()
    assert "digraph" in dot and '"a' in dot
    buf = io.StringIO()
    wf.print_stats(buf)
    assert "a" in buf.getvalue()


# -- prng ------------------------------------------------------------------

def test_prng_registry_deterministic():
    g1 = prng.get("t1")
    a = g1.uniform(-1, 1, (4,))
    g1.seed(g1.state_seed)
    b = g1.uniform(-1, 1, (4,))
    numpy.testing.assert_array_equal(a, b)
    prng.seed_all(99)
    c = prng.get("t1").uniform(-1, 1, (4,))
    prng.seed_all(99)
    d = prng.get("t1").uniform(-1, 1, (4,))
    numpy.testing.assert_array_equal(c, d)
