"""Continuous profiling plane (ISSUE 10, veles/profiling.py):
sampling profiler + speedscope rendering, memory accounting in the
health ring, critical-path analysis over the flight recorder, the
HTTP/CLI surfaces, and the master+2-slave acceptance run."""

import json
import threading
import time
import urllib.request

import pytest

from veles import health, profiling, telemetry
from veles.health import HealthMonitor


@pytest.fixture
def mnist_config_guard():
    """Workflow builders mutate root.mnist without restoring; tests
    here that build workflows must not leak that config into later
    files (same guard as tests/test_health.py)."""
    from veles.config import root
    from veles.znicz_tpu.models import mnist  # noqa: F401
    saved_loader = {k: root.mnist.loader.get(k)
                    for k in ("minibatch_size", "n_train", "n_valid")}
    saved_epochs = root.mnist.decision.get("max_epochs")
    yield
    root.mnist.loader.update(saved_loader)
    root.mnist.decision.max_epochs = saved_epochs


def _busy_thread(stop, name="busy-worker"):
    def spin():
        x = 0
        while not stop.is_set():
            x += 1
    t = threading.Thread(target=spin, daemon=True, name=name)
    t.start()
    return t


def _assert_speedscope_shape(doc):
    """The schema-shape contract a speedscope import needs."""
    assert doc["$schema"] == \
        "https://www.speedscope.app/file-format-schema.json"
    frames = doc["shared"]["frames"]
    assert isinstance(frames, list) and frames
    for f in frames:
        assert isinstance(f["name"], str)
        assert isinstance(f["file"], str)
        assert isinstance(f["line"], int)
    assert isinstance(doc["profiles"], list) and doc["profiles"]
    assert doc["activeProfileIndex"] == 0
    for prof in doc["profiles"]:
        assert prof["type"] == "sampled"
        assert prof["unit"] == "seconds"
        assert isinstance(prof["name"], str)
        assert len(prof["samples"]) == len(prof["weights"])
        total = 0.0
        for sample, weight in zip(prof["samples"], prof["weights"]):
            assert sample, "empty stack sample"
            for idx in sample:
                assert 0 <= idx < len(frames)
            assert weight > 0
            total += weight
        assert prof["endValue"] == pytest.approx(total, abs=1e-3)


# -- the sampler --------------------------------------------------------


def test_speedscope_document_names_threads_and_validates():
    stop = threading.Event()
    _busy_thread(stop, "busy-worker")
    try:
        prof = profiling.capture_profile(0.4, hz=200)
    finally:
        stop.set()
    assert prof.ticks > 10
    doc = prof.to_speedscope()
    _assert_speedscope_shape(doc)
    names = [p["name"] for p in doc["profiles"]]
    assert "busy-worker" in names       # per named thread, folded
    assert "MainThread" in names
    # the sampler never profiles itself
    assert "profiler-sampler" not in names
    # capture honesty metadata
    assert doc["veles"]["ticks"] == prof.ticks
    assert 0.0 <= doc["veles"]["overhead_fraction"] < 1.0


def test_collapsed_stack_render_parses():
    stop = threading.Event()
    _busy_thread(stop, "busy-worker")
    try:
        prof = profiling.capture_profile(0.3, hz=200)
    finally:
        stop.set()
    lines = prof.to_collapsed().splitlines()
    assert lines
    total = 0
    for line in lines:
        stack, count = line.rsplit(" ", 1)
        assert ";" in stack             # thread;frame...;leaf
        total += int(count)
    assert total == prof.ticks
    assert any(line.startswith("busy-worker;") for line in lines)


def test_bounded_aggregate_folds_overflow_into_truncated():
    stop = threading.Event()
    _busy_thread(stop, "busy-a")
    _busy_thread(stop, "busy-b")
    profiler = profiling.SamplingProfiler(hz=300, max_stacks=1)
    profiler.start()
    time.sleep(0.3)
    profiler.stop()
    stop.set()
    prof = profiler.profile()
    assert len(prof.stacks) <= 1 + len(prof.thread_names())
    assert prof.truncated > 0
    assert any(stack == (profiling._TRUNCATED_FRAME,)
               for _, stack in prof.stacks)
    # the truncation is visible in the rendered document too
    assert prof.to_speedscope()["veles"]["truncated_samples"] > 0


def test_profiler_overhead_bound():
    """The default-rate sampler must stay cheap — measured by its own
    accounting: seconds spent walking stacks over the capture wall
    time. Run in isolation this is ~0.5-1%; under the FULL suite the
    process drags dozens of leaked daemon threads (reactors, batcher
    workers, heartbeats from earlier tests), every sample walks all
    of them and GIL waits inflate the self-time, so the unit bound is
    load-tolerant. The < 3% ACCEPTANCE bound is the bench row
    (`profiler_overhead_pct`): the measured throughput delta of the
    MNIST train loop, off vs on — the number that prices what a
    profiled process actually loses."""
    stop = threading.Event()
    _busy_thread(stop)
    try:
        prof = profiling.capture_profile(1.0, hz=profiling.DEFAULT_HZ)
    finally:
        stop.set()
    assert prof.ticks > 40              # it really sampled
    assert prof.overhead_fraction < 0.10, prof.overhead_fraction
    # absolute per-tick cost stays sub-millisecond-scale: a sampler
    # gone O(n^2) (or holding its lock across the frame walk) blows
    # this long before it blows the fraction
    assert prof.self_seconds / prof.ticks < 0.002, \
        prof.self_seconds / prof.ticks


def test_profile_endpoint_params_and_formats():
    code, body, ctype = profiling.profile_endpoint(
        "/debug/profile?seconds=0.05&hz=200")
    assert code == 200 and ctype.startswith("application/json")
    _assert_speedscope_shape(json.loads(body))
    code, body, ctype = profiling.profile_endpoint(
        "/debug/profile?seconds=0.05&format=collapsed")
    assert code == 200 and ctype.startswith("text/plain")
    # garbage params answer 400, never a traceback — including
    # non-finite floats: hz=nan would slip through a min/max clamp
    # (NaN compares False) and busy-spin the sampler at zero delay
    for q in ("seconds=banana", "hz=x", "format=zorp", "hz=nan",
              "hz=inf", "seconds=nan"):
        code, body, _ = profiling.profile_endpoint(
            "/debug/profile?" + q)
        assert code == 400, q
        assert "error" in json.loads(body)
    # constructor defense in depth: a direct NaN hz falls back to the
    # default instead of a zero-period loop
    assert profiling.SamplingProfiler(hz=float("nan")).hz \
        == profiling.DEFAULT_HZ


# -- memory accounting --------------------------------------------------


def test_host_memory_and_gauges_reach_metrics_history():
    mem = profiling.host_memory()
    assert mem["rss_bytes"] > 1 << 20   # a python process holds MBs
    assert mem["open_fds"] > 0
    with health.scoped(HealthMonitor(interval=60.0)) as monitor:
        monitor.tick()
        doc = monitor.history_doc()
        series = doc["series"]
        assert series["veles_host_rss_bytes"][-1][1] > 1 << 20
        assert series["veles_host_open_fds"][-1][1] > 0
        # the perf-ledger size estimate rides the same tick
        assert "veles_perf_ledger_programs" in series
        assert "veles_perf_ledger_est_bytes" in series
    # the gauges landed in the registry too (a /metrics scrape
    # carries them, not only the ring)
    text = telemetry.get_registry().render_prometheus()
    assert "veles_host_rss_bytes" in text


def test_forward_cache_estimate_tracks_params_and_buckets(
        tmp_path, mnist_config_guard):
    # a minimal hand-built archive: no training, no serving fixture
    import numpy
    from veles.serving import ModelRegistry
    w = numpy.zeros((4, 3), numpy.float32)
    numpy.save(tmp_path / "w.npy", w)
    (tmp_path / "contents.json").write_text(json.dumps({
        "format": 1, "workflow": "tiny",
        "input_sample_shape": [4],
        "units": [{"type": "all2all", "name": "fc",
                   "config": {"neurons": 3}, "weights": "w.npy"}],
    }))
    reg = ModelRegistry(backend="numpy")
    try:
        entry = reg.load("tiny", str(tmp_path))
        assert entry.cache_bytes() == w.nbytes   # numpy: one copy
        fam = telemetry.get_registry().gauge(
            "veles_serving_forward_cache_bytes", labels=("model",))
        assert fam.labels("tiny").value == w.nbytes
        reg.unload("tiny")
        assert fam.labels("tiny").value == 0     # gone, reads zero
    finally:
        reg.close()


# -- critical-path analysis ---------------------------------------------


def _span(name, wall, dur, ctx, **args):
    """Inject one wall-anchored span into the flight ring (the
    absorb_remote path — deterministic timestamps)."""
    telemetry.tracer.absorb_remote([{
        "name": name, "wall": wall, "dur": dur, "pid": 1, "tid": 1,
        "args": dict(ctx.span_args(), **args)}])


def test_critical_path_sums_match_hand_computed_fixture():
    tr = telemetry.tracer
    tr.clear()
    now = time.time()
    # job A on slave 1: dispatch 10ms, wire 20ms, compute 60ms,
    # merge 10ms over a 100ms extent (fully attributed)
    a = telemetry.TraceContext.new()
    _span("job.dispatch", now - 10.0, 0.010, a, slave=1, job_id=1)
    _span("job.wire", now - 9.99, 0.020, a, slave=1, job_id=1)
    _span("slave.apply", now - 9.99, 0.010, a, slave=1, job_id=1)
    _span("slave.compute", now - 9.98, 0.040, a, slave=1, job_id=1)
    _span("slave.update_build", now - 9.94, 0.010, a, slave=1,
          job_id=1)
    _span("job.merge", now - 9.91, 0.010, a, slave=1, job_id=1)
    # job B on slave 2: same shape but 3x the compute -> straggler
    b = telemetry.TraceContext.new()
    _span("job.dispatch", now - 5.0, 0.010, b, slave=2, job_id=2)
    _span("job.wire", now - 4.99, 0.020, b, slave=2, job_id=2)
    _span("slave.compute", now - 4.97, 0.180, b, slave=2, job_id=2)
    _span("job.merge", now - 4.79, 0.010, b, slave=2, job_id=2)
    doc = profiling.critical_path_doc(60.0)
    train = doc["train"]
    assert doc["serving"] is None
    assert train["jobs"] == 2
    legs = train["legs"]
    assert legs["dispatch"]["total_s"] == pytest.approx(0.020)
    assert legs["wire"]["total_s"] == pytest.approx(0.040)
    assert legs["compute"]["total_s"] == pytest.approx(0.240)
    assert legs["merge"]["total_s"] == pytest.approx(0.020)
    # extents: A = 100ms, B = 220ms -> everything attributed
    assert train["wall_s"] == pytest.approx(0.320, abs=1e-3)
    assert train["attributed_fraction"] >= 0.99
    assert train["legs"]["compute"]["fraction"] == pytest.approx(
        0.240 / 0.320, abs=0.01)
    # straggler: slave 2, compute-dominated
    assert train["straggler"]["slave"] == "2"
    assert train["straggler"]["leg"] == "compute"
    assert set(train["slaves"]) == {"1", "2"}


def test_critical_path_serving_legs_and_window():
    tr = telemetry.tracer
    tr.clear()
    now = time.time()
    ctx = telemetry.TraceContext.new()
    _span("serving.queue", now - 2.0, 0.004, ctx, model="m")
    _span("serving.execute", now - 1.996, 0.016, ctx, model="m")
    _span("http.predict", now - 2.0, 0.020, ctx, model="m")
    old = telemetry.TraceContext.new()
    _span("serving.execute", now - 500.0, 0.5, old, model="m")
    doc = profiling.critical_path_doc(60.0)
    serve = doc["serving"]
    assert doc["train"] is None
    assert serve["jobs"] == 1           # the old trace fell outside
    assert serve["legs"]["queue"]["total_s"] == pytest.approx(0.004)
    assert serve["legs"]["execute"]["total_s"] == pytest.approx(0.016)
    assert serve["attributed_fraction"] >= 0.99
    # routed through the shared debug endpoint
    routed = telemetry.debug_endpoint(
        "/debug/critical_path?window=60")
    assert routed["serving"]["jobs"] == 1
    assert routed["train"] is None


# -- HTTP + CLI surfaces ------------------------------------------------


def _get_json(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.load(resp)


def test_profile_and_critical_path_over_web_status_http():
    from veles.web_status import WebStatus
    ws = WebStatus(port=0)
    try:
        base = "http://127.0.0.1:%d" % ws.port
        code, doc = _get_json(
            base + "/debug/profile?seconds=0.3&hz=200")
        assert code == 200
        _assert_speedscope_shape(doc)
        names = [p["name"] for p in doc["profiles"]]
        # the capture names the reactor loop and the worker thread
        # the deferred handler itself runs on
        assert "reactor" in names, names
        assert "http-worker" in names, names
        code, doc = _get_json(base + "/debug/critical_path?window=60")
        assert code == 200
        assert set(doc) >= {"window_s", "train", "serving", "traces"}
        # probes keep answering while a capture is in flight (the
        # whole point of the defer)
        t = threading.Thread(
            target=lambda: urllib.request.urlopen(
                base + "/debug/profile?seconds=1.2", timeout=30).read(),
            daemon=True)
        t.start()
        time.sleep(0.2)
        t0 = time.perf_counter()
        code, _ = _get_json(base + "/healthz")
        assert code == 200
        assert time.perf_counter() - t0 < 0.5
        t.join(timeout=30)
    finally:
        ws.close()


def test_profile_served_on_serving_frontend_too():
    """The tentpole wires BOTH HTTP planes: the serving frontend
    serves /debug/profile (deferred) and /debug/critical_path like
    web-status does — even with an empty registry."""
    from veles.serving import ModelRegistry
    from veles.serving.frontend import ServingFrontend
    reg = ModelRegistry(backend="numpy")
    front = ServingFrontend(reg, port=0)
    try:
        base = "http://127.0.0.1:%d" % front.port
        code, doc = _get_json(
            base + "/debug/profile?seconds=0.2&hz=200")
        assert code == 200
        _assert_speedscope_shape(doc)
        code, doc = _get_json(base + "/debug/critical_path")
        assert code == 200 and "train" in doc
    finally:
        front.close()
        reg.close()


def test_rss_slo_fires_on_memory_threshold():
    """Memory trajectories are SLO-able: a threshold objective over
    the ring's veles_host_rss_bytes series fires when RSS exceeds the
    bound (the leak-alert path the ISSUE asks for)."""
    with health.scoped(HealthMonitor(interval=60.0)) as monitor:
        now = time.time()
        monitor.tick(now=now)
        slo = monitor.add_slo({
            "name": "rss_leak", "series": "veles_host_rss_bytes",
            "op": "<=", "threshold": 1.0,        # 1 byte: must trip
            "target": 0.99, "fast_window": 30, "slow_window": 60})
        monitor.tick(now=now + 1)
        assert slo.firing
        ready, reasons = monitor.ready_state()
        assert not ready
        assert any("rss_leak" in r for r in reasons)


def test_velescli_profile_cli_roundtrip(tmp_path, capsys):
    from veles.__main__ import profile_main
    from veles.web_status import WebStatus
    ws = WebStatus(port=0)
    try:
        out = tmp_path / "prof.json"
        rc = profile_main(["http://127.0.0.1:%d" % ws.port,
                           "--seconds", "0.3", "--hz", "200",
                           "--out", str(out)])
        assert rc == 0
        captured = capsys.readouterr().out
        assert "thread(s)" in captured
        assert "reactor" in captured
        doc = json.loads(out.read_text())
        _assert_speedscope_shape(doc)
        # a 200 that is NOT a speedscope document exits 2 (here:
        # /status.json answers JSON of the wrong shape)
        rc = profile_main(["http://127.0.0.1:%d/status.json"
                           % ws.port])
        assert rc == 2
    finally:
        ws.close()
    # unreachable endpoint exits 2, never a traceback
    assert profile_main(["http://127.0.0.1:1", "--seconds",
                         "0.1"]) == 2


def test_velescli_profile_rejects_malformed_indices(capsys):
    """A 200 whose document passes the outer shape check but carries
    out-of-range frame indices (version skew, buggy server) must exit
    2, not traceback in the summary loop."""
    import http.server
    import socketserver
    from veles.__main__ import profile_main

    evil = json.dumps({
        "shared": {"frames": [{"name": "f", "file": "", "line": 1}]},
        "profiles": [{"type": "sampled", "name": "t",
                      "unit": "seconds", "startValue": 0,
                      "endValue": 1.0, "samples": [[0, 99]],
                      "weights": [1.0]}]}).encode()

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", str(len(evil)))
            self.end_headers()
            self.wfile.write(evil)

        def log_message(self, *a):
            pass

    httpd = socketserver.TCPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        rc = profile_main(["http://127.0.0.1:%d"
                           % httpd.server_address[1]])
        assert rc == 2
        assert "error:" in capsys.readouterr().err
    finally:
        httpd.shutdown()
        httpd.server_close()


# -- velescli top rendering ---------------------------------------------


def test_top_renders_rss_lag_and_breakdown_side_by_side():
    from veles.fleet import render_snapshot
    snap = {
        "ts": 0.0,
        "fleet": {"targets": 2, "reachable": 2, "ready": 1,
                  "slaves": 2, "firing_slos": [], "degraded": []},
        "targets": [
            {"url": "http://a:1", "reachable": True, "ready": True,
             "role": "master",
             "metrics": {"reactor_lag_s": 0.0004,
                         "host_rss_bytes": 191889408},
             "critical_path": {
                 "train": {
                     "jobs": 12,
                     "legs": {
                         "dispatch": {"fraction": 0.02},
                         "wire": {"fraction": 0.31},
                         "compute": {"fraction": 0.62},
                         "merge": {"fraction": 0.05}},
                     "straggler": {"slave": "3", "leg": "compute"}},
                 "serving": None}},
            # pre-PR-10 target: no RSS, no critical path — the row
            # renders without error
            {"url": "http://b:2", "reachable": True, "ready": None,
             "role": "process", "metrics": {}},
        ],
    }
    out = render_snapshot(snap)
    assert "rss 183.0MB, reactor lag 0.4ms" in out
    assert "step: dispatch 2% | wire 31% | compute 62% | merge 5%" \
        in out
    assert "straggler slave 3: compute" in out
    assert "b:2" in out                 # degraded row still present


def test_top_degrades_against_pre_pr10_target(capsys):
    """A live process WITHOUT the new surfaces (no /debug/critical_
    path, no veles_host_* gauges) scrapes into a normal row — no
    error key, no crash (the graceful-degradation satellite)."""
    import http.server
    import socketserver

    class OldHandler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.startswith("/healthz"):
                body = b'{"status": "ok"}'
                self.send_response(200)
            else:
                body = b"not found"
                self.send_response(404)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = socketserver.TCPServer(("127.0.0.1", 0), OldHandler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        from veles.fleet import render_snapshot, scrape_target
        row = scrape_target(
            "http://127.0.0.1:%d" % httpd.server_address[1],
            timeout=5.0)
        assert row["reachable"] and row["live"]
        assert "error" not in row
        assert "critical_path" not in row
        assert "host_rss_bytes" not in row.get("metrics", {})
        # and it renders
        snap = {"ts": 0.0, "targets": [row],
                "fleet": {"targets": 1, "reachable": 1, "ready": 0,
                          "slaves": 0, "firing_slos": [],
                          "degraded": []}}
        assert row["url"].replace("http://", "") in \
            render_snapshot(snap)
    finally:
        httpd.shutdown()
        httpd.server_close()


# -- acceptance: real master + 2 slaves ---------------------------------


def test_profiling_acceptance_master_two_slaves(mnist_config_guard):
    """ISSUE 10 acceptance: on a real master + 2-slave run,
    /debug/profile returns valid speedscope JSON naming the reactor
    and worker threads, and /debug/critical_path attributes the bulk
    of each job's wall time to the dispatch/wire/compute/merge legs
    consistently with the flight-recorder spans."""
    from tests.test_service import make_wf
    from veles.client import SlaveClient
    from veles.server import MasterServer
    from veles.web_status import WebStatus

    telemetry.tracer.clear()
    master_wf = make_wf("ProfMaster")
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2)
    server.start_background()
    ws = WebStatus(port=0)
    try:
        address = "127.0.0.1:%d" % server.bound_address[1]
        base = "http://127.0.0.1:%d" % ws.port
        threads, ok = [], [0, 0]

        def pump(i):
            wf = make_wf("ProfSlave%d" % i)
            wf.is_slave = True
            ok[i] = SlaveClient(wf, address,
                                name="prof-%d" % i).run_forever()

        for i in range(2):
            t = threading.Thread(target=pump, args=(i,))
            t.start()
            threads.append(t)
        # capture WHILE the cluster trains: the profile must name the
        # live threads doing the work
        code, prof = _get_json(
            base + "/debug/profile?seconds=0.5&hz=200")
        assert code == 200
        _assert_speedscope_shape(prof)
        names = [p["name"] for p in prof["profiles"]]
        assert "reactor" in names, names
        assert "http-worker" in names, names
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads)
        assert sum(ok) >= 4             # the cluster really trained
        code, doc = _get_json(base + "/debug/critical_path?window=300")
        assert code == 200
        train = doc["train"]
        assert train is not None and train["jobs"] >= 4
        # >= 90% of per-job wall time lands in the four legs, and the
        # leg sums agree with the raw flight-recorder spans
        assert train["attributed_fraction"] >= 0.9, train
        spans = telemetry.tracer.flight_spans(300.0)
        raw = {}
        for _, ev in spans:
            leg = profiling._TRAIN_LEGS.get(ev["name"])
            if leg and (ev.get("args") or {}).get("trace_id"):
                raw[leg] = raw.get(leg, 0.0) + ev["dur"] / 1e6
        for leg in ("dispatch", "wire", "compute", "merge"):
            assert train["legs"][leg]["total_s"] == pytest.approx(
                raw.get(leg, 0.0), rel=0.05, abs=1e-4), leg
        # every slave that served jobs is attributed; the straggler
        # names one of them
        assert len(train["slaves"]) == sum(1 for n in ok if n)
        assert train["straggler"]["slave"] in train["slaves"]
    finally:
        ws.close()
        server.request_stop()
