"""Genetics GA + ensemble (SURVEY.md §2.7 rows 8-9, L9)."""

import json
import os
import subprocess
import sys

import numpy
import pytest

import veles.prng as prng
from veles.config import Config, Tune, root
from veles.genetics import GeneticOptimizer, apply_values

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ga_minimizes_quadratic():
    """Pure-function sanity: the GA finds the box minimum."""
    tunables = {"a": Tune(5.0, -10.0, 10.0),
                "b": Tune(-3.0, -10.0, 10.0)}

    def evaluate(v):
        return (v["a"] - 2.0) ** 2 + (v["b"] - 7.0) ** 2

    opt = GeneticOptimizer(evaluate, tunables, population_size=16,
                           generations=12, seed=3)
    best, fitness = opt.run()
    assert fitness < 0.5, (best, fitness)
    assert abs(best["a"] - 2.0) < 0.6
    assert abs(best["b"] - 7.0) < 0.6


def test_ga_respects_discrete_and_bounds():
    tunables = {"n": Tune(4, 2, 16)}
    seen = []

    def evaluate(v):
        seen.append(v["n"])
        return abs(v["n"] - 9)

    opt = GeneticOptimizer(evaluate, tunables, population_size=12,
                           generations=8, seed=1)
    best, fitness = opt.run()
    assert all(isinstance(n, int) and 2 <= n <= 16 for n in seen)
    assert best["n"] == 9 and fitness == 0


def test_ga_failed_individuals_are_skipped():
    tunables = {"x": Tune(0.0, -1.0, 1.0)}

    def evaluate(v):
        if v["x"] < 0:
            raise RuntimeError("diverged")
        return v["x"]

    opt = GeneticOptimizer(evaluate, tunables, population_size=8,
                           generations=3, seed=2)
    best, fitness = opt.run()
    assert numpy.isfinite(fitness) and best["x"] >= 0


def test_find_and_apply_values():
    from veles.genetics import find_tunables
    cfg = Config("test_ga")
    cfg.update({"layer": {"lr": Tune(0.1, 0.001, 1.0)}})
    cfg.layers = [{"<-": {"lr": Tune(0.2, 0.01, 0.5)}}]
    found = find_tunables(cfg)
    assert set(found) == {"layer/lr", "layers/0/<-/lr"}
    apply_values(cfg, {"layer/lr": 0.25, "layers/0/<-/lr": 0.3})
    assert cfg.layer.lr == 0.25
    assert cfg.layers[0]["<-"]["lr"] == 0.3


def test_ga_improves_mnist_config():
    """The acceptance criterion from VERDICT: GA demonstrably improves
    a (deliberately mistuned) MNIST config."""
    import copy

    from veles.genetics import optimize_config
    from veles.znicz_tpu.models import mnist
    saved_layers = copy.deepcopy(root.mnist.layers)
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    root.mnist.loader.update(
        {"n_train": 200, "n_valid": 80, "minibatch_size": 40})
    root.mnist.decision.max_epochs = 2
    # mistuned lr, marked searchable
    for layer in root.mnist.layers:
        if "<-" in layer:
            layer["<-"]["learning_rate"] = Tune(1e-4, 1e-4, 0.1)

    def run_one():
        prng.seed_all(1234)
        wf = mnist.create_workflow(name="GAMnist")
        wf.initialize(device="numpy")
        wf.run()
        return float(wf.decision.best_metric)

    try:
        baseline = run_one()   # defaults = the mistuned lr
        opt = optimize_config(root.mnist, run_one,
                              population_size=5, generations=2, seed=9)
    finally:
        root.mnist.layers = saved_layers
        root.mnist.loader.update(saved)
        root.mnist.decision.max_epochs = 5
    assert opt.best_fitness <= baseline, \
        (opt.best_fitness, baseline)
    assert opt.best_fitness < baseline - 0.05, \
        "GA failed to improve the mistuned lr"


def test_ensemble_beats_or_matches_members():
    from veles.ensemble import Ensemble
    from veles.znicz_tpu.models import mnist
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    root.mnist.loader.update(
        {"n_train": 300, "n_valid": 100, "minibatch_size": 50})
    root.mnist.decision.max_epochs = 2

    def factory(name):
        return mnist.create_workflow(name=name)

    try:
        ens = Ensemble(factory, n_models=3, base_seed=42,
                       device="numpy")
        ens.train()
        report = ens.evaluate_classification()
    finally:
        root.mnist.loader.update(saved)
        root.mnist.decision.max_epochs = 5
    assert report["n_valid"] == 100
    assert len(report["member_errors"]) == 3
    # mean-of-softmax must not be worse than the weakest member
    assert report["ensemble_error"] <= max(report["member_errors"]), \
        report


def test_cli_optimize_smoke(tmp_path):
    """--optimize end-to-end through velescli (config file marks the
    lr searchable with Tune, reference-style)."""
    cfg = tmp_path / "ga_config.py"
    cfg.write_text(
        "from veles.config import root, Tune\n"
        "for layer in root.mnist.layers:\n"
        "    if '<-' in layer:\n"
        "        layer['<-']['learning_rate'] = "
        "Tune(0.02, 0.005, 0.1)\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [sys.executable, "-m", "veles",
         os.path.join(REPO, "veles/znicz_tpu/models/mnist.py"),
         str(cfg),
         "root.mnist.loader.n_train=120",
         "root.mnist.loader.n_valid=40",
         "root.mnist.loader.minibatch_size=40",
         "root.mnist.decision.max_epochs=1",
         "-d", "numpy", "--seed", "5", "--no-stats",
         "--optimize", "1x3"],
        env=env, cwd=REPO, capture_output=True, text=True,
        timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    doc = json.loads(r.stdout.strip().splitlines()[-1])
    assert numpy.isfinite(doc["best_fitness"])
    assert doc["evaluations"] >= 3


def test_ga_parallel_matches_sequential(tmp_path):
    """A ProcessPoolMap generation scores EXACTLY like a sequential
    one (results in population order, per-individual seeding), and the
    search is deterministic given the seed — the rebuild's answer to
    the reference farming GA individuals to slaves."""
    from veles.genetics import (
        GeneticOptimizer, ProcessPoolMap, SubprocessTrainer,
        find_tunables)
    from veles.config import Tune, root

    cfg = tmp_path / "ga_config.py"
    cfg.write_text(
        "from veles.config import root, Tune\n"
        "for layer in root.mnist.layers:\n"
        "    if '<-' in layer:\n"
        "        layer['<-']['learning_rate'] = "
        "Tune(0.02, 0.005, 0.1)\n"
        "root.mnist.loader.n_train = 120\n"
        "root.mnist.loader.n_valid = 40\n"
        "root.mnist.loader.minibatch_size = 40\n"
        "root.mnist.decision.max_epochs = 1\n")
    wf_path = os.path.join(REPO, "veles/znicz_tpu/models/mnist.py")
    # tunables must match what the workers will see: workflow module
    # first (its defaults create root.mnist.layers), config on top —
    # Main.run ordering
    import veles.__main__ as vmain
    vmain.import_file(wf_path, "ga_wf_probe")
    vmain.import_file(str(cfg), "ga_cfg_probe")
    tunables = find_tunables(root)
    assert tunables, "config file produced no Tune leaves"

    def search(map_fn):
        evaluate = SubprocessTrainer(
            wf_path, str(cfg), seed=5, device="numpy")
        opt = GeneticOptimizer(
            evaluate, dict(tunables), generations=1,
            population_size=3, elite=1, seed=5, map_fn=map_fn)
        opt.run()
        return opt

    try:
        seq = search(None)
        with ProcessPoolMap(2) as pmap:
            par = search(pmap)
    finally:
        # the sequential path evaluates IN-PROCESS (config file + Tune
        # application mutate root.mnist, including the layer dicts in
        # place): re-executing the sample module restores its defaults
        # wholesale so later test modules see a clean tree
        vmain.import_file(wf_path, "ga_wf_probe")
    assert seq.evaluations == par.evaluations >= 4
    assert numpy.isfinite(par.best_fitness)
    # parallel == sequential: same champions, same fitness history
    assert [f for f, _ in seq.history] == [f for f, _ in par.history]
    assert seq.best_fitness == par.best_fitness
    assert seq.best_values == par.best_values


def test_cli_optimize_parallel_smoke(tmp_path):
    """--optimize GENSxPOPxWORKERS end-to-end through velescli."""
    cfg = tmp_path / "ga_config.py"
    cfg.write_text(
        "from veles.config import root, Tune\n"
        "for layer in root.mnist.layers:\n"
        "    if '<-' in layer:\n"
        "        layer['<-']['learning_rate'] = "
        "Tune(0.02, 0.005, 0.1)\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [sys.executable, "-m", "veles",
         os.path.join(REPO, "veles/znicz_tpu/models/mnist.py"),
         str(cfg),
         "root.mnist.loader.n_train=120",
         "root.mnist.loader.n_valid=40",
         "root.mnist.loader.minibatch_size=40",
         "root.mnist.decision.max_epochs=1",
         "-d", "numpy", "--seed", "5", "--no-stats",
         "--optimize", "1x3x2"],
        env=env, cwd=REPO, capture_output=True, text=True,
        timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    doc = json.loads(r.stdout.strip().splitlines()[-1])
    assert numpy.isfinite(doc["best_fitness"])
    assert doc["evaluations"] >= 4
    assert doc["workers"] == 2


# -- GA over slaves (SURVEY §2.7 "runs distributed over slaves") ------

def _quad_fitness(values):
    """Picklable deterministic fitness for the slave-dispatch tests."""
    return (values["a/lr"] - 0.37) ** 2


def _slow_quad_fitness(values):
    """Same, but slower than the timeout-drop test's slave_timeout."""
    import time
    time.sleep(0.6)
    return _quad_fitness(values)


def test_ga_slave_survives_timeout_drop():
    """A healthy slave whose evaluation outlives the master's
    slave_timeout gets dropped (its task requeues) — it must
    RECONNECT, re-register under a fresh id, re-report the finished
    result, and keep serving, instead of mistaking the closed socket
    for a finished search and exiting (ADVICE r4 medium: with every
    evaluation longer than the timeout, a non-reconnecting pool
    drains one task per slave into a silent livelock). One slave,
    slave_timeout far below the evaluation time: the search can only
    complete through the reconnect path."""
    import threading
    import time
    from veles.genetics import GATaskServer, _SafeEval, ga_slave_loop

    with GATaskServer("127.0.0.1:0", slave_timeout=0.25) as server:
        addr = "127.0.0.1:%d" % server.bound_address[1]
        t_slave = threading.Thread(
            target=ga_slave_loop, args=(addr,),
            kwargs={"name": "slow", "reconnect_delay": 0.05},
            daemon=True)
        t_slave.start()
        done = {}
        t_map = threading.Thread(
            target=lambda: done.update(out=server.map(
                _SafeEval(_slow_quad_fitness),
                [{"a/lr": v} for v in (0.1, 0.3)])),
            daemon=True)
        t_map.start()
        t_map.join(timeout=30)
        assert not t_map.is_alive(), \
            "map() livelocked: dropped slave never came back"
        assert [r[0] for r in done["out"]] == [
            pytest.approx((v - 0.37) ** 2) for v in (0.1, 0.3)]
        # the slave really was dropped and re-registered at least once
        assert server._next_slave > 2
    t_slave.join(timeout=10)
    assert not t_slave.is_alive()


def test_ga_over_slaves_matches_sequential():
    """One GA search dispatched over TWO in-process slaves through the
    HMAC-framed task server equals the sequential run bit-for-bit
    (every individual carries its own deterministic evaluation), and
    the topology records both slaves serving."""
    import threading
    from veles.config import Tune
    from veles.genetics import (
        GATaskServer, GeneticOptimizer, ga_slave_loop)

    tun = {"a/lr": Tune(0.1, 0.01, 1.0)}
    seq = GeneticOptimizer(_quad_fitness, dict(tun), generations=3,
                           population_size=6, seed=11)
    seq.run()

    with GATaskServer("127.0.0.1:0") as server:
        addr = "127.0.0.1:%d" % server.bound_address[1]
        threads = [threading.Thread(
            target=ga_slave_loop, args=(addr,),
            kwargs={"name": "slave%d" % i}, daemon=True)
            for i in range(2)]
        for t in threads:
            t.start()
        par = GeneticOptimizer(_quad_fitness, dict(tun), generations=3,
                               population_size=6, seed=11,
                               map_fn=server)
        par.run()
        status = server.status()
    for t in threads:
        t.join(timeout=5)
    assert par.best_fitness == seq.best_fitness
    assert par.best_values == seq.best_values
    assert [f for f, _ in par.history] == [f for f, _ in seq.history]
    assert status["n_slaves"] >= 1


def test_ga_requeue_protocol_level():
    """The drop->requeue contract, exercised DIRECTLY: a slave takes a
    task and dies before reporting — drop_slave must put exactly that
    task back at the head of the pending pool, and a completed task
    must NOT requeue on a later drop of the same slave."""
    import threading
    from veles.genetics import GATaskServer, _SafeEval

    with GATaskServer("127.0.0.1:0") as server:
        # two registered slaves, three tasks
        sid_a = server._handle(("hello", "a"))[1]
        sid_b = server._handle(("hello", "b"))[1]
        fn = _SafeEval(_quad_fitness)
        done = {}
        t = threading.Thread(
            target=lambda: done.update(
                out=server.map(fn, [{"a/lr": v}
                                    for v in (0.1, 0.2, 0.3)])),
            daemon=True)
        t.start()
        import time
        for _ in range(100):
            if server.queue or server.tasks:
                break
            time.sleep(0.01)
        kind, idx_a, fn_a, vals_a, epoch = server._handle(
            ("task", sid_a))
        assert kind == "task"
        # slave A dies holding idx_a: it must return to the pool head
        server.drop_slave(sid_a)
        assert server.queue[0] == idx_a
        assert sid_a not in server.inflight
        # slave B drains everything (including the requeued task)
        while len(server.results) < 3:
            resp = server._handle(("task", sid_b))
            if resp[0] != "task":
                time.sleep(0.01)
                continue
            _, idx, fn_b, vals, ep = resp
            server._handle(("result", sid_b, idx, fn_b(vals), ep))
        # completed tasks must not resurrect when B later drops
        server.drop_slave(sid_b)
        assert not server.queue or all(
            i not in server.results for i in server.queue)
        t.join(timeout=10)
        assert not t.is_alive()
        assert [r[0] for r in done["out"]] == [
            pytest.approx((v - 0.37) ** 2) for v in (0.1, 0.2, 0.3)]
        # a STALE-generation re-report (a dropped slave finishing
        # after its generation completed) is acknowledged but
        # discarded — it must not poison a later map()'s results
        before = dict(server.results)
        assert server._handle(
            ("result", sid_b, 0, -1.0, epoch - 1)) == ("ok",)
        assert server.results == before


def test_ga_slave_churn_late_join_elasticity():
    """Slave churn over the real sockets: a short-lived slave serves
    one task and leaves cleanly; a slave joining MID-GENERATION picks
    up the rest and the search completes. (The die-while-HOLDING-a-
    task requeue path is covered at protocol level by
    test_ga_requeue_protocol_level — a clean exit after the result
    ack leaves nothing in flight to requeue.)"""
    import threading
    import time
    from veles.config import Tune
    from veles.genetics import (
        GATaskServer, GeneticOptimizer, ga_slave_loop)

    tun = {"a/lr": Tune(0.1, 0.01, 1.0)}
    with GATaskServer("127.0.0.1:0") as server:
        addr = "127.0.0.1:%d" % server.bound_address[1]
        # slave A serves exactly one task, then disconnects
        a = threading.Thread(target=ga_slave_loop, args=(addr,),
                             kwargs={"name": "mortal", "max_tasks": 1},
                             daemon=True)
        a.start()
        opt = GeneticOptimizer(_quad_fitness, dict(tun), generations=1,
                               population_size=5, seed=7,
                               map_fn=server)
        done = {}

        def search():
            done["opt"] = opt.run()

        t = threading.Thread(target=search, daemon=True)
        t.start()
        time.sleep(0.3)   # let the mortal slave take+finish one task
        b = threading.Thread(target=ga_slave_loop, args=(addr,),
                             kwargs={"name": "survivor"}, daemon=True)
        b.start()
        t.join(timeout=30)
        assert not t.is_alive(), "generation never completed"
    assert numpy.isfinite(opt.best_fitness)
    # initial pop (5) + one child generation minus the 2 elites (3)
    assert opt.evaluations == 8
