"""zlint (veles/analysis/) — rule-by-rule fixtures + the repo gate.

Each rule gets a minimal violating snippet that must FIRE and a
corrected (or pragma'd) version that must stay QUIET; the CLI contract
(exit codes, sorted JSON shape) is pinned; and the tier-1 gate at the
bottom runs the full analyzer over the installed ``veles`` package and
asserts zero findings — every rule violation introduced anywhere in
the tree from now on fails CI until fixed or pragma'd with a reason.
No device needed: everything here is pure AST work.
"""

import json
import os

import pytest

from veles.analysis import analyze_paths
from veles.analysis.cli import lint_main


def lint_src(tmp_path, source, relname="mod.py", select=None):
    """Write ``source`` at ``relname`` under tmp and analyze it."""
    path = tmp_path / relname
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(source)
    return analyze_paths([str(path)], base=str(tmp_path),
                         select=select)


def rule_ids(findings):
    return [f.rule for f in findings]


# -- tracer-purity -----------------------------------------------------

_PURITY_BAD = """\
import numpy
import time


class Op:
    def xla_run(self, ctx):
        x = ctx.get("x")
        numpy.random.rand(3)
        time.time()
        print("traced")
        bad = x.sum().item()
        worse = float(x)
        self.cache = bad + worse
        return self.helper(ctx)

    def helper(self, ctx):
        self.hidden = 1
"""

_PURITY_GOOD = """\
import numpy


def _shape_prod(shape):
    return int(numpy.prod(shape))


class Op:
    def xla_run(self, ctx):
        x = ctx.get("x")
        n = _shape_prod((3, 4))
        return x.sum() / n
"""


def test_tracer_purity_fires_on_all_impurities(tmp_path):
    findings = lint_src(tmp_path, _PURITY_BAD,
                        relname="znicz_tpu/ops/fake.py",
                        select=["tracer-purity"])
    msgs = "\n".join(f.message for f in findings)
    assert "numpy.random.rand" in msgs
    assert "time.time" in msgs
    assert "print()" in msgs
    assert ".item()" in msgs
    assert "float()" in msgs
    assert "mutates self.cache" in msgs
    # the self.helper() call is followed: its mutation is caught too
    assert "mutates self.hidden" in msgs


def test_tracer_purity_quiet_on_pure_op_and_outside_ops(tmp_path):
    assert lint_src(tmp_path, _PURITY_GOOD,
                    relname="znicz_tpu/ops/fake.py",
                    select=["tracer-purity"]) == []
    # same impure source OUTSIDE znicz_tpu/ops is not traced code
    assert lint_src(tmp_path, _PURITY_BAD, relname="host_unit.py",
                    select=["tracer-purity"]) == []


def test_tracer_purity_catches_every_import_spelling(tmp_path):
    # the bans must not be dodgeable by import style
    src = """\
from numpy import random
from time import monotonic
import numpy.random
import time as clock


class Op:
    def xla_run(self, ctx):
        random.rand(3)
        monotonic()
        numpy.random.standard_normal(2)
        clock.sleep(0.1)
        return ctx.get("x")
"""
    findings = lint_src(tmp_path, src,
                        relname="znicz_tpu/ops/fake.py",
                        select=["tracer-purity"])
    msgs = "\n".join(f.message for f in findings)
    assert "random.rand" in msgs
    assert "monotonic" in msgs
    assert "numpy.random.standard_normal" in msgs
    assert "clock.sleep" in msgs
    assert len(findings) == 4


def test_tracer_purity_follows_module_alias_helpers(tmp_path):
    # `H.noisy(x)` — the dominant helper-call style in ops/ — must be
    # followed into the helper module
    helpers = """\
import numpy


def noisy(x):
    return x + numpy.random.uniform()
"""
    op = """\
from znicz_tpu.ops import helpers as H
from znicz_tpu.ops.helpers import noisy


class Op:
    def xla_run(self, ctx):
        a = H.noisy(ctx.get("x"))
        return noisy(a)
"""
    (tmp_path / "znicz_tpu" / "ops").mkdir(parents=True)
    (tmp_path / "znicz_tpu" / "ops" / "helpers.py").write_text(helpers)
    (tmp_path / "znicz_tpu" / "ops" / "op.py").write_text(op)
    findings = analyze_paths([str(tmp_path)], base=str(tmp_path),
                             select=["tracer-purity"])
    assert len(findings) == 1          # shared helper reported ONCE
    assert findings[0].rule == "tracer-purity"
    assert "numpy.random.uniform" in findings[0].message
    assert findings[0].file.endswith("helpers.py")


def test_tracer_purity_taint_propagates_through_locals(tmp_path):
    # float(s) where s DERIVES from a ctx read concretizes a tracer
    # just as surely as float(ctx.get(...)) does
    src = """\
class Op:
    def xla_run(self, ctx):
        t = ctx.get("x")
        s = t * 2
        k = float(s)
        return k
"""
    findings = lint_src(tmp_path, src,
                        relname="znicz_tpu/ops/fake.py",
                        select=["tracer-purity"])
    assert rule_ids(findings) == ["tracer-purity"]
    assert "float()" in findings[0].message


def test_tracer_purity_int_on_static_shapes_is_legitimate(tmp_path):
    src = """\
import numpy


class Op:
    def xla_run(self, ctx):
        n = int(numpy.prod((2, 3)))
        return n
"""
    assert lint_src(tmp_path, src, relname="znicz_tpu/ops/fake.py",
                    select=["tracer-purity"]) == []


# -- lock-order --------------------------------------------------------

_LOCK_CYCLE = """\
import threading


class A:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def m1(self):
        with self._a:
            with self._b:
                pass

    def m2(self):
        with self._b:
            with self._a:
                pass
"""

_LOCK_ORDERED = """\
import threading


class A:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def m1(self):
        with self._a:
            with self._b:
                pass

    def m2(self):
        with self._a:
            with self._b:
                pass
"""


def test_lock_order_cycle_fires(tmp_path):
    findings = lint_src(tmp_path, _LOCK_CYCLE,
                        select=["lock-order"])
    assert rule_ids(findings) == ["lock-order"]
    assert "cycle" in findings[0].message


def test_lock_order_quiet_on_consistent_order(tmp_path):
    assert lint_src(tmp_path, _LOCK_ORDERED,
                    select=["lock-order"]) == []


def test_lock_order_interprocedural_reentry(tmp_path):
    # the deadlock spans two methods: r1 holds the non-reentrant lock
    # and CALLS r2, which takes it again
    src = """\
import threading


class A:
    def __init__(self):
        self._a = threading.Lock()

    def r1(self):
        with self._a:
            self.r2()

    def r2(self):
        with self._a:
            pass
"""
    findings = lint_src(tmp_path, src, select=["lock-order"])
    assert rule_ids(findings) == ["lock-order"]
    assert "re-acquired" in findings[0].message
    # an RLock makes the same shape legal
    assert lint_src(tmp_path, src.replace("threading.Lock",
                                          "threading.RLock"),
                    select=["lock-order"]) == []


def test_lock_order_sees_inside_except_handlers(tmp_path):
    # retry/error paths are exactly where this codebase takes locks;
    # handler bodies must not be a blind spot
    src = """\
import threading


class A:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def m1(self):
        try:
            pass
        except Exception:
            with self._a:
                with self._b:
                    pass

    def m2(self):
        with self._b:
            with self._a:
                pass
"""
    findings = lint_src(tmp_path, src, select=["lock-order"])
    assert rule_ids(findings) == ["lock-order"]
    assert "cycle" in findings[0].message


def test_lock_order_multi_item_with_statement(tmp_path):
    # `with self.a, self.b:` orders a before b exactly like nesting
    src = """\
import threading


class A:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()

    def m1(self):
        with self.a, self.b:
            pass

    def m2(self):
        with self.b:
            with self.a:
                pass
"""
    findings = lint_src(tmp_path, src, select=["lock-order"])
    assert rule_ids(findings) == ["lock-order"]
    assert "cycle" in findings[0].message
    # `with self.a, self.a:` deadlocks immediately on a Lock
    dup = """\
import threading


class A:
    def __init__(self):
        self.a = threading.Lock()

    def m1(self):
        with self.a, self.a:
            pass
"""
    findings = lint_src(tmp_path, dup, select=["lock-order"])
    assert rule_ids(findings) == ["lock-order"]
    assert "re-acquired" in findings[0].message


def test_lock_order_follows_inherited_attr_binding(tmp_path):
    # self.store is bound by the BASE __init__; the subclass's
    # `with self._big: self.store.put()` must still record the
    # _big -> Store._lock ordering edge (white-box: edges feed the
    # cycle detector, and a dropped edge = an invisible deadlock)
    src = """\
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()

    def put(self):
        with self._lock:
            pass


class Base:
    def __init__(self):
        self._big = threading.Lock()
        self.store = Store()


class Child(Base):
    def f(self):
        with self._big:
            self.store.put()
"""
    from veles.analysis.core import build_project
    from veles.analysis.rules_threads import _LockWalker
    path = tmp_path / "m.py"
    path.write_text(src)
    proj = build_project([str(path)], base=str(tmp_path))
    walker = _LockWalker(proj)
    mod = proj.modules[0]
    for cls in mod.classes.values():
        for mname, meth in cls.methods.items():
            walker.walk_function(mod, cls, meth, [],
                                 ["%s.%s" % (cls.name, mname)])
    assert (("Base", "_big"), ("Store", "_lock")) in walker.edges


def test_lock_order_resolves_inherited_locks(tmp_path):
    # a subclass re-acquiring the non-reentrant lock its BASE bound
    # in __init__ is a guaranteed runtime deadlock; per-class-only
    # lookup used to lint it clean
    src = """\
import threading


class Base:
    def __init__(self):
        self._lock = threading.Lock()


class Child(Base):
    def work(self):
        with self._lock:
            self.helper()

    def helper(self):
        with self._lock:
            pass
"""
    findings = lint_src(tmp_path, src, select=["lock-order"])
    assert rule_ids(findings) == ["lock-order"]
    assert "re-acquired" in findings[0].message
    # the graph node is keyed by the DEFINING class
    assert "Base._lock" in findings[0].message


# -- unguarded-shared-state --------------------------------------------

_RACE = """\
import threading


class W:
    def __init__(self):
        self._lock = threading.Lock()
        threading.Thread(target=self._work, daemon=True).start()

    def _work(self):
        self.value = 1

    def set_value(self, v):
        self.value = v
"""


def test_unguarded_shared_state_fires(tmp_path):
    findings = lint_src(tmp_path, _RACE,
                        select=["unguarded-shared-state"])
    assert rule_ids(findings) == ["unguarded-shared-state"]
    assert "W.value" in findings[0].message


def test_unguarded_shared_state_sees_except_handler_writes(tmp_path):
    src = _RACE.replace(
        "        self.value = 1",
        "        try:\n"
        "            pass\n"
        "        except Exception:\n"
        "            self.value = 1")
    findings = lint_src(tmp_path, src,
                        select=["unguarded-shared-state"])
    assert rule_ids(findings) == ["unguarded-shared-state"]


def test_unguarded_shared_state_positional_target(tmp_path):
    # Thread(group, target, ...) — the positional spelling races
    # exactly like target=
    src = _RACE.replace(
        "threading.Thread(target=self._work, daemon=True).start()",
        "threading.Thread(None, self._work, daemon=True).start()")
    findings = lint_src(tmp_path, src,
                        select=["unguarded-shared-state"])
    assert rule_ids(findings) == ["unguarded-shared-state"]


def test_unguarded_shared_state_quiet_when_locked(tmp_path):
    src = _RACE.replace(
        "        self.value = 1",
        "        with self._lock:\n            self.value = 1"
    ).replace(
        "        self.value = v",
        "        with self._lock:\n            self.value = v")
    assert lint_src(tmp_path, src,
                    select=["unguarded-shared-state"]) == []


def test_unguarded_shared_state_across_inheritance(tmp_path):
    # base class starts the thread, SUBCLASS adds the racing public
    # method — per-class pairing used to lint this clean
    src = """\
import threading


class Base:
    def __init__(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        self.x = 1


class Api(Base):
    def set_x(self, v):
        self.x = v
"""
    findings = lint_src(tmp_path, src,
                        select=["unguarded-shared-state"])
    assert rule_ids(findings) == ["unguarded-shared-state"]
    assert ".x is written" in findings[0].message


def test_unguarded_shared_state_honours_inherited_lock(tmp_path):
    # writes guarded by a lock the BASE class bound must count as
    # locked, not fire as false positives
    src = """\
import threading


class Base:
    def __init__(self):
        self._lock = threading.Lock()


class W(Base):
    def __init__(self):
        super().__init__()
        threading.Thread(target=self._work, daemon=True).start()

    def _work(self):
        with self._lock:
            self.value = 1

    def push(self, v):
        with self._lock:
            self.value = v
"""
    assert lint_src(tmp_path, src,
                    select=["unguarded-shared-state"]) == []


# -- checkpoint-state --------------------------------------------------

_STATEFUL_UNIT = """\
class Counter(Unit):
    def run(self):
        self.count = getattr(self, "count", 0) + 1
"""


def test_checkpoint_state_fires(tmp_path):
    findings = lint_src(tmp_path, _STATEFUL_UNIT,
                        select=["checkpoint-state"])
    assert rule_ids(findings) == ["checkpoint-state"]
    assert "Counter.run() mutates self.count" in findings[0].message


def test_checkpoint_state_quiet_with_get_state_or_pragma(tmp_path):
    with_state = _STATEFUL_UNIT + (
        "\n    def get_state(self):\n"
        "        return {\"count\": self.count}\n")
    assert lint_src(tmp_path, with_state,
                    select=["checkpoint-state"]) == []
    pragma = _STATEFUL_UNIT.replace(
        "class Counter(Unit):",
        "class Counter(Unit):  "
        "# zlint: disable=checkpoint-state (ephemeral demo)")
    assert lint_src(tmp_path, pragma,
                    select=["checkpoint-state"]) == []


def test_checkpoint_state_inherited_get_state_counts(tmp_path):
    src = """\
class Base(Unit):
    def get_state(self):
        return {}


class Derived(Base):
    def run(self):
        self.n = 1
"""
    assert lint_src(tmp_path, src, select=["checkpoint-state"]) == []


# -- telemetry-hygiene -------------------------------------------------


def test_telemetry_hygiene_loop_creation_fires(tmp_path):
    src = """\
from veles import telemetry


def hot(n):
    for i in range(n):
        telemetry.counter("veles_x_total", "help").inc()
"""
    findings = lint_src(tmp_path, src, select=["telemetry-hygiene"])
    assert rule_ids(findings) == ["telemetry-hygiene"]
    assert "inside a loop" in findings[0].message
    hoisted = """\
from veles import telemetry


def hot(n):
    c = telemetry.counter("veles_x_total", "help")
    for i in range(n):
        c.inc()
"""
    assert lint_src(tmp_path, hoisted,
                    select=["telemetry-hygiene"]) == []


def test_telemetry_hygiene_formatted_name_in_loop_fires(tmp_path):
    # a name formatted per iteration leaks one family per value —
    # the WORSE failure mode must not be exempt from the loop check
    src = """\
from veles import telemetry


def leak(names):
    for n in names:
        telemetry.counter("veles_%s_total" % n, "help").inc()
"""
    findings = lint_src(tmp_path, src, select=["telemetry-hygiene"])
    assert rule_ids(findings) == ["telemetry-hygiene"]


def test_telemetry_hygiene_sees_registry_handle_style(tmp_path):
    # `reg = telemetry.get_registry()` handles are what the runtime
    # actually uses — the loop check must reach them too
    src = """\
from veles import telemetry


def leak(names):
    reg = telemetry.get_registry()
    for n in names:
        reg.counter("veles_%s_total" % n, "help").inc()
"""
    findings = lint_src(tmp_path, src, select=["telemetry-hygiene"])
    assert rule_ids(findings) == ["telemetry-hygiene"]


def test_telemetry_hygiene_identity_label_fires(tmp_path):
    src = """\
def label_it(fam, obj):
    fam.labels(id(obj)).inc()
"""
    findings = lint_src(tmp_path, src, select=["telemetry-hygiene"])
    assert rule_ids(findings) == ["telemetry-hygiene"]
    assert "identity" in findings[0].message
    bounded = """\
def label_it(fam, kind):
    fam.labels(kind).inc()
"""
    assert lint_src(tmp_path, bounded,
                    select=["telemetry-hygiene"]) == []


def test_telemetry_hygiene_identity_span_name_fires(tmp_path):
    # the tracing twin of identity labels (ISSUE 6 satellite): a span
    # NAME minted per request is unbounded name cardinality — every
    # formatted spelling must fire, across receiver shapes
    src = """\
from veles import telemetry


def serve(tracer, job_id, token):
    with telemetry.span("job-%s" % job_id):
        pass
    with tracer.span(f"req.{token}"):
        pass
    telemetry.tracer.add_complete("j.{}".format(job_id), 0.0, 1.0)
"""
    findings = lint_src(tmp_path, src, select=["telemetry-hygiene"])
    assert rule_ids(findings) == ["telemetry-hygiene"] * 3
    assert "span name" in findings[0].message


def test_telemetry_hygiene_span_identity_in_args_quiet(tmp_path):
    # the sanctioned spelling: constant name, identity in the ARGS —
    # and non-identity formatting (unit/kind names) stays legal
    src = """\
from veles import telemetry


def serve(tracer, job_id, kind):
    with telemetry.span("job.serve", job_id=job_id):
        pass
    telemetry.tracer.add_complete("xla.dispatch.%s" % kind, 0.0, 1.0)


class Unit:
    def run(self, tracer):
        tracer.add_complete("%s.run" % self.name, 0.0, 1.0)
"""
    assert lint_src(tmp_path, src,
                    select=["telemetry-hygiene"]) == []


def test_telemetry_hygiene_wire_label_fires(tmp_path):
    # ISSUE 18: a .labels(...) value read straight off the wire lets
    # callers mint series at will — every spelling of the read fires
    src = """\
def count(fam, request):
    fam.labels(request.headers.get("x-veles-tenant")).inc()
    fam.labels(request.headers["x-api-key"]).inc()
    fam.labels("t-%s" % request.body).inc()
"""
    findings = lint_src(tmp_path, src, select=["telemetry-hygiene"])
    assert rule_ids(findings) == ["telemetry-hygiene"] * 3
    assert "headers/body" in findings[0].message
    assert "resolve" in findings[0].hint


def test_telemetry_hygiene_wire_label_resolver_quiet(tmp_path):
    # the sanctioned spelling: the raw header passes through a
    # bounded *resolve* call (unknown keys fold to one bucket), or is
    # resolved into a plain local before labelling
    src = """\
def count(fam, table, request):
    fam.labels(table.resolve(request.headers.get("x-tenant"))).inc()
    tenant = table.resolve(request.headers.get("x-tenant"))
    fam.labels(tenant).inc()
"""
    assert lint_src(tmp_path, src,
                    select=["telemetry-hygiene"]) == []


def test_telemetry_hygiene_wire_label_pragma(tmp_path):
    src = """\
def count(fam, request):
    fam.labels(request.headers.get("x-t")).inc()  \
# zlint: disable=telemetry-hygiene (bounded by proxy upstream)
"""
    assert lint_src(tmp_path, src,
                    select=["telemetry-hygiene"]) == []


def test_telemetry_hygiene_span_rule_ignores_foreign_span(tmp_path):
    # .span on a non-telemetry receiver (e.g. a regex Match.span or a
    # geometry object) must not fire, whatever the argument looks like
    src = """\
def shape(layout, col_id):
    return layout.span("cell-%s" % col_id)
"""
    assert lint_src(tmp_path, src,
                    select=["telemetry-hygiene"]) == []


# -- thread-lifecycle --------------------------------------------------


def test_thread_lifecycle_fires_without_daemon_or_join(tmp_path):
    src = """\
import threading


def spawn(work):
    t = threading.Thread(target=work)
    t.start()
"""
    findings = lint_src(tmp_path, src, select=["thread-lifecycle"])
    assert rule_ids(findings) == ["thread-lifecycle"]


def test_thread_lifecycle_sees_aliased_threading_module(tmp_path):
    src = """\
import threading as th


def spawn(work):
    th.Thread(target=work).start()
"""
    findings = lint_src(tmp_path, src, select=["thread-lifecycle"])
    assert rule_ids(findings) == ["thread-lifecycle"]
    # other modules' Thread attribute is NOT the constructor
    other = """\
import notthreading


def spawn(work):
    notthreading.Thread(target=work).start()
"""
    assert lint_src(tmp_path, other,
                    select=["thread-lifecycle"]) == []


def test_thread_lifecycle_quiet_on_daemon_or_join(tmp_path):
    daemon = """\
import threading


def spawn(work):
    threading.Thread(target=work, daemon=True).start()
"""
    assert lint_src(tmp_path, daemon,
                    select=["thread-lifecycle"]) == []
    joined = """\
import threading


def spawn(work):
    t = threading.Thread(target=work)
    t.start()
    t.join()
"""
    assert lint_src(tmp_path, joined,
                    select=["thread-lifecycle"]) == []
    # `t.daemon = True` before start() is the standard idiom and
    # just as shutdown-safe as the constructor keyword
    attr_daemon = """\
import threading


def spawn(work):
    t = threading.Thread(target=work)
    t.daemon = True
    t.start()
"""
    assert lint_src(tmp_path, attr_daemon,
                    select=["thread-lifecycle"]) == []


# -- probe-purity ------------------------------------------------------

_PROBE_BAD = """\
import urllib.request


class Handler:
    def do_GET(self):
        if self.path == "/healthz":
            with self.server.lock:
                doc = self.master.status()
            self._reply(200, doc)
        elif self.path.startswith("/readyz"):
            body = urllib.request.urlopen(
                "http://peer:8080/metrics").read()
            self._reply(200, body)
        else:
            self._reply(404, {})
"""

_PROBE_GOOD = """\
class Handler:
    def do_GET(self):
        if self.path.startswith(("/healthz", "/readyz")):
            code, doc = monitor.probe(self.path)
            self._reply(code, doc)
        elif self.path.startswith("/metrics"):
            with self.lock:
                body = registry.render_prometheus()
            self._reply(200, body)
        else:
            self._reply(404, {})
"""


def test_probe_purity_fires_on_blocking_probe_branches(tmp_path):
    """Satellite (ISSUE 8): /healthz taking a lock + pulling live
    status, /readyz fetching over the network — every blocking shape
    fires; the hint points at the cached-verdict contract."""
    findings = lint_src(tmp_path, _PROBE_BAD, select=["probe-purity"])
    assert set(rule_ids(findings)) == {"probe-purity"}
    messages = " | ".join(f.message for f in findings)
    assert "context-managed" in messages       # the with-lock
    assert "'status'" in messages              # the live state pull
    assert "'urlopen'" in messages             # the network fetch
    assert len(findings) >= 3


def test_probe_purity_quiet_on_cached_reads_and_other_routes(tmp_path):
    """The compliant shape — probe branches read the monitor's cached
    verdict — is quiet, and a with-lock in a NON-probe branch
    (/metrics) is out of scope for this rule."""
    assert lint_src(tmp_path, _PROBE_GOOD,
                    select=["probe-purity"]) == []


def test_probe_purity_pragma_suppresses(tmp_path):
    src = """\
class Handler:
    def do_GET(self):
        if self.path == "/healthz":
            with self.lock:  # zlint: disable=probe-purity (test rig)
                doc = dict(self.cache)
            self._reply(200, doc)
"""
    assert lint_src(tmp_path, src, select=["probe-purity"]) == []


# -- reactor-purity ----------------------------------------------------

_REACTOR_BAD = """\
import time
from urllib.request import urlopen


class Session:
    def on_frame(self, obj):
        time.sleep(0.1)                 # parks the whole loop
        return self.sock.recv(4096)     # raw-socket wait


class Plane:
    def __init__(self, loop):
        loop.call_soon(self._merge)
        loop.every(1.0, self._sweep)
        loop.call_later(0.5, lambda: self.done.wait())

    def _merge(self):
        self.worker.join()              # Thread.join shape
        return urlopen("http://127.0.0.1:1/metrics")

    def _sweep(self):
        self.sock.sendall(b"tick")
"""

_REACTOR_GOOD = """\
class Session:
    def on_frame(self, obj):
        resp = self.handle(obj)
        with self.lock:                 # existing lock discipline: ok
            self.counter += 1
        self.send_obj(resp)
        return ", ".join(str(x) for x in resp)   # str.join, not Thread


class Plane:
    def __init__(self, loop):
        loop.call_soon(self._merge, 1)
        loop.every(1.0, self._sweep)

    def _merge(self, n):
        self.pending.append(n)

    def _sweep(self):
        for conn in self.connections():
            if conn.stale:
                conn.close()

    def off_loop_helper(self):
        # NOT a reactor callback: blocking here is the worker
        # thread's whole job
        self.done.wait(2.0)
"""


def test_reactor_purity_fires_on_blocking_callbacks(tmp_path):
    """Satellite (ISSUE 9): sleep + raw recv inside on_frame, and
    join/urlopen/sendall/Event.wait inside call_soon/every/call_later
    targets (incl. a lambda) all fire."""
    findings = lint_src(tmp_path, _REACTOR_BAD,
                        select=["reactor-purity"])
    assert set(rule_ids(findings)) == {"reactor-purity"}
    messages = " | ".join(f.message for f in findings)
    for name in ("'sleep'", "'recv'", "'join'", "'urlopen'",
                 "'sendall'", "'wait'"):
        assert name in messages, (name, messages)
    assert len(findings) >= 6


def test_reactor_purity_quiet_on_pure_callbacks(tmp_path):
    """The compliant shapes are quiet: locks (the existing handle()
    discipline), str.join, queue appends, conn.close sweeps — and
    blocking calls in methods that are NOT reactor callbacks are out
    of scope."""
    assert lint_src(tmp_path, _REACTOR_GOOD,
                    select=["reactor-purity"]) == []


def test_reactor_purity_pragma_suppresses(tmp_path):
    src = """\
import time


class S:
    def on_timer(self):
        time.sleep(0.01)  # zlint: disable=reactor-purity (test rig)
"""
    assert lint_src(tmp_path, src, select=["reactor-purity"]) == []


# -- profiler-safety ---------------------------------------------------

_PROFILER_BAD = """\
from veles import profiling


class Status:
    def _route(self, request):
        if request.path.startswith("/debug/profile"):
            code, body, ctype = profiling.profile_endpoint(
                request.path)
            request.reply(code, body, ctype)


class Wire:
    def on_frame(self, obj):
        self.profiler.start()
        prof = profiling.capture_profile(2.0)
        self.profiler.stop()
        return prof


class Plane:
    def __init__(self, loop, profiler):
        loop.every(1.0, self._tick)
        self._profiler = profiler

    def _tick(self):
        self._profiler.capture()
"""

_PROFILER_GOOD = """\
from veles import profiling


class Status:
    def _route(self, request):
        if request.path.startswith("/debug/profile"):
            request.defer(self._serve_profile, request)
        elif request.path.startswith("/debug/"):
            request.reply_json(200, {})

    def _serve_profile(self, request):
        # worker thread: blocking here is the whole point
        code, body, ctype = profiling.profile_endpoint(request.path)
        request.reply(code, body, ctype)


def bench_row():
    # NOT a reactor callback or route: a bench/CLI capture is fine
    profiler = profiling.SamplingProfiler()
    profiler.start()
    profiler.stop()
    return profiler.profile()


class Wire:
    def on_frame(self, obj):
        # unrelated receivers named start/stop stay quiet
        self.timer.start()
        self.timer.stop()
"""


def test_profiler_safety_fires_on_inline_captures(tmp_path):
    """Satellite (ISSUE 10): a /debug/profile branch answering inline
    (no defer, direct profile_endpoint), profiler start/stop +
    capture_profile inside on_frame, and .capture() inside an every()
    target all fire."""
    findings = lint_src(tmp_path, _PROFILER_BAD,
                        select=["profiler-safety"])
    assert set(rule_ids(findings)) == {"profiler-safety"}
    messages = " | ".join(f.message for f in findings)
    assert "'profile_endpoint'" in messages      # inline route call
    assert "'capture_profile'" in messages       # on_frame capture
    assert "profiler.start" in messages          # start on the loop
    assert "_profiler.capture" in messages       # scheduled target
    assert len(findings) >= 5


def test_profiler_safety_quiet_on_deferred_and_offloop(tmp_path):
    """The compliant shapes: the route branch defers to a worker (the
    blocking body lives in the deferred method), a bench/CLI capture
    off the loop, and non-profiler .start()/.stop() receivers."""
    assert lint_src(tmp_path, _PROFILER_GOOD,
                    select=["profiler-safety"]) == []


def test_profiler_safety_pragma_suppresses(tmp_path):
    src = """\
class S:
    def on_timer(self):
        self.profiler.start()  # zlint: disable=profiler-safety (rig)
"""
    assert lint_src(tmp_path, src, select=["profiler-safety"]) == []


# -- wire-schema -------------------------------------------------------

_WIRE_MISMATCH = """\
def recv_frame(sock):
    return sock


class Master:
    def handle(self, request):
        if request[0] == "job":
            return ("job", request, 1, 2, 3)
        return ("ok",)


def pump(sock):
    resp = recv_frame(sock)
    if resp[0] == "job":
        _, payload, job_id, epoch = resp
        return payload, job_id, epoch
"""

_WIRE_GUARDED = """\
def recv_frame(sock):
    return sock


class Master:
    def handle(self, request):
        if request[0] == "job":
            return ("job", request, 1, 2, 3)
        return ("ok",)


def pump(sock):
    resp = recv_frame(sock)
    if resp[0] != "job" or len(resp) < 4:
        return None
    _, payload, job_id, epoch = resp[:4]
    trace = resp[4] if len(resp) > 4 else None
    return payload, job_id, epoch, trace


def pump_skew_tolerant(sock):
    resp = recv_frame(sock)
    if resp[0] == "job":
        try:
            _, payload, job_id = resp
        except ValueError:
            return None
        return payload, job_id
"""


def test_wire_schema_arity_mismatch_fires(tmp_path):
    """The seeded mismatch (ISSUE 12 satellite): producer ships a
    5-tuple, consumer tuple-unpacks 4 without a slice guard."""
    findings = lint_src(tmp_path, _WIRE_MISMATCH,
                        select=["wire-schema"])
    assert rule_ids(findings) == ["wire-schema"]
    assert "5-tuple" in findings[0].message
    assert "ValueError" in findings[0].message


def test_wire_schema_index_past_producer_fires(tmp_path):
    src = _WIRE_MISMATCH.replace(
        "        _, payload, job_id, epoch = resp\n"
        "        return payload, job_id, epoch",
        "        return resp[5]")
    findings = lint_src(tmp_path, src, select=["wire-schema"])
    assert rule_ids(findings) == ["wire-schema"]
    assert "element 5" in findings[0].message


def test_wire_schema_quiet_on_guarded_consumers(tmp_path):
    """Every mixed-version-safe spelling stays quiet: the early-exit
    len guard + slice unpack, the conditional-expression len guard,
    and the try/except ValueError skew handler."""
    assert lint_src(tmp_path, _WIRE_GUARDED,
                    select=["wire-schema"]) == []


def test_wire_schema_directions_are_separate_namespaces(tmp_path):
    # the request ("job", sid, lease) 3-tuple and the response
    # ("job", payload, job_id, epoch, trace) 5-tuple share a kind;
    # a response consumer must be judged against response producers
    # only, or every protocol with symmetric kinds false-positives
    src = """\
def send_frame(sock, obj):
    pass


def recv_frame(sock):
    return sock


class Master:
    def handle(self, request):
        if request[0] == "job":
            return ("job", request, 1, 2, 3)
        return ("ok",)


def pump(sock):
    send_frame(sock, ("job", 7, "lease"))
    resp = recv_frame(sock)
    if resp[0] != "job" or len(resp) < 4:
        return None
    _, payload, job_id, epoch = resp[:4]
    return payload, job_id, epoch
"""
    assert lint_src(tmp_path, src, select=["wire-schema"]) == []


def test_wire_schema_no_producer_is_quiet(tmp_path):
    # a kind the analyzer never sees produced (an external peer)
    # cannot be judged — arbitrary [0] == "str" code must not fire
    src = """\
def route(argv):
    if argv[0] == "serve":
        return argv[1]
"""
    assert lint_src(tmp_path, src, select=["wire-schema"]) == []


def test_wire_schema_floor_guard_excludes_short_producers(tmp_path):
    # mixed-version producers (2-tuple and 4-tuple welcome): the
    # canonical `len(resp) < 4: return` guard makes the short
    # variant unreachable at the unpack, so the exact unpack of 4
    # must be judged against the 4-tuple producer only
    src = """\
def recv_frame(sock):
    return sock


class Master:
    def handle(self, request):
        if len(request) < 3:
            return ("welcome", 1)
        return ("welcome", 1, 2, 3)


def connect(sock):
    resp = recv_frame(sock)
    if resp[0] != "welcome" or len(resp) < 4:
        return None
    _, a, b, c = resp
    return a, b, c
"""
    assert lint_src(tmp_path, src, select=["wire-schema"]) == []


def test_wire_schema_pragma_suppresses(tmp_path):
    src = _WIRE_MISMATCH.replace(
        "        _, payload, job_id, epoch = resp",
        "        _, payload, job_id, epoch = resp  "
        "# zlint: disable=wire-schema (peer ships 4)")
    assert lint_src(tmp_path, src, select=["wire-schema"]) == []


# -- resource-leak -----------------------------------------------------

_LEAK_ON_EXC = """\
import socket


def build():
    return 1


def fetch(addr):
    sock = socket.create_connection(addr)
    meta = build()
    sock.close()
    return meta
"""

_LEAK_SAFE = """\
import socket


def build():
    return 1


def fetch(addr):
    sock = socket.create_connection(addr)
    try:
        meta = build()
    finally:
        sock.close()
    return meta


def fetch_handler(addr):
    sock = socket.create_connection(addr)
    try:
        meta = build()
    except OSError:
        sock.close()
        raise
    sock.close()
    return meta


def stored(self, addr):
    sock = socket.create_connection(addr)
    self.sock = sock
    return self


def handed_off(addr, conns):
    sock = socket.create_connection(addr)
    conns.append(sock)
"""


def test_resource_leak_on_exception_path_fires(tmp_path):
    """The leak-on-exception fixture (ISSUE 12 satellite): the bench
    MasterServer class of bug — a risky call between acquire and
    release with no try/finally."""
    findings = lint_src(tmp_path, _LEAK_ON_EXC,
                        select=["resource-leak"])
    assert rule_ids(findings) == ["resource-leak"]
    assert "build()" in findings[0].message
    assert findings[0].line == 9          # anchored at the acquire


def test_resource_leak_never_released_fires(tmp_path):
    src = """\
import socket


def probe(addr):
    sock = socket.create_connection(addr)
    return sock.getpeername()[0]
"""
    findings = lint_src(tmp_path, src, select=["resource-leak"])
    assert rule_ids(findings) == ["resource-leak"]
    assert "never released" in findings[0].message


def test_resource_leak_discarded_grant_fires(tmp_path):
    src = """\
def admit(pool):
    pool.grant()
"""
    findings = lint_src(tmp_path, src, select=["resource-leak"])
    assert rule_ids(findings) == ["resource-leak"]
    assert "discarded" in findings[0].message


def test_resource_leak_quiet_on_safe_shapes(tmp_path):
    """try/finally, except-release-reraise, attribute store and
    container hand-off all own the resource correctly."""
    assert lint_src(tmp_path, _LEAK_SAFE,
                    select=["resource-leak"]) == []


def test_resource_leak_quiet_on_with_and_slot_store(tmp_path):
    src = """\
def read(path, pool, active, req):
    with open(path) as f:
        data = f.read()
    req.slot = pool.grant()
    active[req.slot] = req
    return data
"""
    assert lint_src(tmp_path, src, select=["resource-leak"]) == []


def test_resource_leak_sibling_branch_is_not_a_path(tmp_path):
    # the else-arm of the acquiring if is mutually exclusive with
    # the acquisition — its calls are not on any path where the
    # resource is live
    src = """\
import socket


def make_other():
    return None


def connect(addr, fast):
    if fast:
        sock = socket.create_connection(addr)
    else:
        sock = make_other()
    try:
        data = sock.recv(1)
    finally:
        sock.close()
    return data
"""
    assert lint_src(tmp_path, src, select=["resource-leak"]) == []


def test_resource_leak_pragma_suppresses(tmp_path):
    src = _LEAK_ON_EXC.replace(
        "    sock = socket.create_connection(addr)",
        "    sock = socket.create_connection(addr)  "
        "# zlint: disable=resource-leak (test rig)")
    assert lint_src(tmp_path, src, select=["resource-leak"]) == []


# -- loop-exception-safety ---------------------------------------------

_LOOP_RAISE = """\
class Session:
    def on_frame(self, obj):
        self.dispatch(obj)

    def dispatch(self, obj):
        if not obj:
            raise ValueError("empty frame")
        return obj
"""

_LOOP_SAFE = """\
class Session:
    def on_frame(self, obj):
        try:
            self.dispatch(obj)
        except (ValueError, KeyError):
            self.reply_error()

    def dispatch(self, obj):
        if not obj:
            raise ValueError("empty frame")
        return obj

    def reply_error(self):
        pass


class Stub:
    def on_frame(self, obj):
        raise NotImplementedError


class Fenced(ConnectionError):
    pass


class Plane:
    def __init__(self, loop):
        loop.every(1.0, self._tick)

    def _tick(self):
        try:
            self.sync()
        except OSError:
            pass

    def sync(self):
        raise Fenced("lease revoked")
"""


def test_loop_exception_uncaught_chain_fires(tmp_path):
    findings = lint_src(tmp_path, _LOOP_RAISE,
                        select=["loop-exception-safety"])
    assert rule_ids(findings) == ["loop-exception-safety"]
    assert "ValueError" in findings[0].message
    assert "Session.on_frame -> Session.dispatch" \
        in findings[0].message


def test_loop_exception_scheduled_target_fires(tmp_path):
    src = """\
class Plane:
    def __init__(self, loop):
        loop.every(1.0, self._tick)

    def _tick(self):
        raise RuntimeError("wedged")
"""
    findings = lint_src(tmp_path, src,
                        select=["loop-exception-safety"])
    assert rule_ids(findings) == ["loop-exception-safety"]
    assert "RuntimeError" in findings[0].message


def test_loop_exception_quiet_on_caught_chains(tmp_path):
    """A try anywhere on the chain covers the raise — including
    through the exception HIERARCHY (a ConnectionError subclass is
    caught by except OSError) — and NotImplementedError stubs are
    the abstract-method convention, not a loop hazard."""
    assert lint_src(tmp_path, _LOOP_SAFE,
                    select=["loop-exception-safety"]) == []


def test_loop_exception_handler_body_is_outside_its_try(tmp_path):
    # a raise INSIDE the except handler is not protected by the
    # handler's own try — the classic error-path-raises bug
    src = """\
class Session:
    def on_frame(self, obj):
        try:
            self.dispatch(obj)
        except ValueError:
            raise RuntimeError("bad frame")

    def dispatch(self, obj):
        return obj
"""
    findings = lint_src(tmp_path, src,
                        select=["loop-exception-safety"])
    assert rule_ids(findings) == ["loop-exception-safety"]
    assert "RuntimeError" in findings[0].message


def test_loop_exception_pragma_suppresses(tmp_path):
    src = _LOOP_RAISE.replace(
        '            raise ValueError("empty frame")',
        '            raise ValueError("empty frame")  '
        '# zlint: disable=loop-exception-safety (severing intended)')
    assert lint_src(tmp_path, src,
                    select=["loop-exception-safety"]) == []


# -- hygiene: bare-except / unused-import / unused-variable ------------


def test_bare_except_fires_and_named_is_quiet(tmp_path):
    src = "try:\n    pass\nexcept:\n    pass\n"
    findings = lint_src(tmp_path, src, select=["bare-except"])
    assert rule_ids(findings) == ["bare-except"]
    named = src.replace("except:", "except Exception:")
    assert lint_src(tmp_path, named, select=["bare-except"]) == []


def test_unused_import_fires_and_noqa_is_quiet(tmp_path):
    src = "import os\nimport sys\n\nprint(sys.argv)\n"
    findings = lint_src(tmp_path, src, select=["unused-import"])
    assert ["unused-import"] == rule_ids(findings)
    assert "'os'" in findings[0].message
    noqa = src.replace("import os", "import os  # noqa: F401")
    assert lint_src(tmp_path, noqa, select=["unused-import"]) == []
    # __init__.py is a re-export surface: exempt wholesale
    assert lint_src(tmp_path, src, relname="pkg/__init__.py",
                    select=["unused-import"]) == []


def test_unused_variable_fires_and_exemptions_hold(tmp_path):
    src = """\
def f(x):
    dead = x + 1
    return x
"""
    findings = lint_src(tmp_path, src, select=["unused-variable"])
    assert rule_ids(findings) == ["unused-variable"]
    assert "'dead'" in findings[0].message
    # underscore names, closure reads and locals() users are exempt
    quiet = """\
def f(x):
    _dead = x + 1
    kept = x + 2

    def g():
        return kept
    return g


def h(x):
    maybe_dead = x
    return locals()
"""
    assert lint_src(tmp_path, quiet, select=["unused-variable"]) == []


# -- pragma engine -----------------------------------------------------


def test_pragma_disable_all_and_multi_rule(tmp_path):
    src = ("try:\n    pass\n"
           "except:  # zlint: disable=all (fixture)\n    pass\n")
    assert lint_src(tmp_path, src, select=["bare-except"]) == []
    multi = ("try:\n    pass\n"
             "except:  # zlint: disable=unused-import,bare-except\n"
             "    pass\n")
    assert lint_src(tmp_path, multi, select=["bare-except"]) == []


def test_pragma_inside_string_literal_is_not_a_pragma(tmp_path):
    src = ('S = "# zlint: disable=bare-except"\n'
           "try:\n    pass\nexcept:\n    pass\n")
    findings = lint_src(tmp_path, src, select=["bare-except"])
    assert rule_ids(findings) == ["bare-except"]


def test_pragma_on_other_line_does_not_suppress(tmp_path):
    src = ("# zlint: disable=bare-except\n"
           "try:\n    pass\nexcept:\n    pass\n")
    findings = lint_src(tmp_path, src, select=["bare-except"])
    assert rule_ids(findings) == ["bare-except"]


# -- CLI contract ------------------------------------------------------


def test_cli_exit_codes(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("X = 1\n")
    assert lint_main([str(clean)]) == 0
    dirty = tmp_path / "dirty.py"
    dirty.write_text("try:\n    pass\nexcept:\n    pass\n")
    assert lint_main([str(dirty)]) == 1
    out = capsys.readouterr().out
    assert "bare-except" in out and "1 finding(s)" in out
    assert lint_main([str(tmp_path / "missing.py")]) == 2
    assert lint_main(["--select", "no-such-rule", str(clean)]) == 2
    # an unparseable input is a usage error, NOT a "findings" verdict
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    capsys.readouterr()
    assert lint_main([str(broken)]) == 2
    assert "cannot parse" in capsys.readouterr().err


def test_cli_unreadable_input_is_usage_error(tmp_path, monkeypatch,
                                             capsys):
    # PermissionError (or any transient FS failure) must exit 2, not
    # traceback with the "findings" code 1
    import builtins
    target = tmp_path / "locked.py"
    target.write_text("X = 1\n")
    real_open = builtins.open

    def deny(path, *args, **kwargs):
        if str(path) == str(target):
            raise PermissionError(13, "Permission denied", str(path))
        return real_open(path, *args, **kwargs)

    monkeypatch.setattr(builtins, "open", deny)
    assert lint_main([str(target)]) == 2
    assert "cannot read" in capsys.readouterr().err


def test_cli_json_is_sorted_and_shaped(tmp_path, capsys):
    a = tmp_path / "a.py"
    a.write_text("import os\n\ntry:\n    pass\nexcept:\n    pass\n")
    b = tmp_path / "b.py"
    b.write_text("try:\n    pass\nexcept:\n    pass\n")
    cwd = os.getcwd()
    os.chdir(tmp_path)        # repo-relative paths in the output
    try:
        rc = lint_main(["--json", str(a), str(b)])
    finally:
        os.chdir(cwd)
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert [sorted(f) for f in payload] == [
        ["file", "hint", "line", "message", "rule", "severity"]
    ] * len(payload)
    keys = [(f["file"], f["line"], f["rule"]) for f in payload]
    assert keys == sorted(keys), "JSON findings must be CI-diffable"
    assert all(not os.path.isabs(f["file"]) for f in payload)
    # byte-stable across runs
    os.chdir(tmp_path)
    try:
        lint_main(["--json", str(a), str(b)])
    finally:
        os.chdir(cwd)
    assert json.loads(capsys.readouterr().out) == payload


def test_cli_list_rules_names_every_registered_rule(capsys):
    from veles.analysis import RULES
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ("tracer-purity", "lock-order",
                    "unguarded-shared-state", "checkpoint-state",
                    "telemetry-hygiene", "thread-lifecycle",
                    "wire-schema", "resource-leak",
                    "loop-exception-safety",
                    "bare-except", "unused-import", "unused-variable"):
        assert rule_id in out
        assert rule_id in RULES


def test_cli_sarif_shape_and_stability(tmp_path, capsys):
    """--format sarif: a valid SARIF 2.1.0 skeleton (ruleId, level,
    artifactLocation/region anchors, the rule table), byte-stable
    across runs, exit-code contract unchanged."""
    p = tmp_path / "m.py"
    p.write_text("try:\n    pass\nexcept:\n    pass\n")
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        rc = lint_main(["--format", "sarif", str(p)])
        first = capsys.readouterr().out
        rc2 = lint_main(["--format", "sarif", str(p)])
        second = capsys.readouterr().out
    finally:
        os.chdir(cwd)
    assert rc == 1 and rc2 == 1
    assert first == second, "SARIF must be byte-stable"
    doc = json.loads(first)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "zlint"
    assert [r["id"] for r in run["tool"]["driver"]["rules"]] \
        == ["bare-except"]
    result = run["results"][0]
    assert result["ruleId"] == "bare-except"
    assert result["level"] == "error"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "m.py"
    assert loc["region"]["startLine"] == 3
    assert "hint:" in result["message"]["text"]
    # clean tree: rc 0, empty results, still valid SARIF
    clean = tmp_path / "clean.py"
    clean.write_text("X = 1\n")
    assert lint_main(["--format", "sarif", str(clean)]) == 0
    empty = json.loads(capsys.readouterr().out)
    assert empty["runs"][0]["results"] == []


def test_cli_json_flag_is_format_alias(tmp_path, capsys):
    p = tmp_path / "m.py"
    p.write_text("try:\n    pass\nexcept:\n    pass\n")
    assert lint_main(["--json", str(p)]) == 1
    legacy = capsys.readouterr().out
    assert lint_main(["--format", "json", str(p)]) == 1
    assert capsys.readouterr().out == legacy


def _git(tmp_path, *argv):
    import subprocess
    return subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t"]
        + list(argv), cwd=tmp_path, check=True, capture_output=True)


def test_cli_changed_only_lints_only_changed_files(tmp_path, capsys):
    """--changed-only: the committed-but-unchanged violation is
    skipped, the modified and the untracked files are linted; exit
    codes keep the 0/1 contract."""
    _git(tmp_path, "init", "-q")
    a = tmp_path / "a.py"
    a.write_text("try:\n    pass\nexcept:\n    pass\n")
    b = tmp_path / "b.py"
    b.write_text("X = 1\n")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "seed")
    b.write_text("try:\n    pass\nexcept:\n    pass\n")
    c = tmp_path / "c.py"                 # untracked
    c.write_text("try:\n    pass\nexcept:\n    pass\n")
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        rc = lint_main([str(tmp_path), "--changed-only",
                        "--select", "bare-except"])
        out = capsys.readouterr().out
        # with nothing changed vs HEAD the changed set is empty:
        # clean exit, zero findings
        _git(tmp_path, "add", ".")
        _git(tmp_path, "commit", "-qm", "all dirty now clean")
        rc_clean = lint_main([str(tmp_path), "--changed-only",
                              "--select", "bare-except"])
        out_clean = capsys.readouterr().out
    finally:
        os.chdir(cwd)
    assert rc == 1
    assert "b.py" in out and "c.py" in out
    assert "a.py" not in out
    assert rc_clean == 0 and "0 finding(s)" in out_clean


def test_cli_changed_only_bad_ref_is_usage_error(tmp_path, capsys):
    # a typo'd ref must hit the documented exit-2 contract, never
    # silently degrade to a full-tree run
    _git(tmp_path, "init", "-q")
    p = tmp_path / "a.py"
    p.write_text("X = 1\n")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "seed")
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        rc = lint_main([str(tmp_path), "--changed-only",
                        "no-such-ref"])
    finally:
        os.chdir(cwd)
    assert rc == 2
    assert "cannot resolve ref" in capsys.readouterr().err


def test_cli_changed_only_falls_back_without_git(tmp_path, capsys):
    # outside any repository the fast mode degrades to the full
    # tree, loudly
    p = tmp_path / "m.py"
    p.write_text("try:\n    pass\nexcept:\n    pass\n")
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        rc = lint_main([str(p), "--changed-only",
                        "--select", "bare-except"])
    finally:
        os.chdir(cwd)
    captured = capsys.readouterr()
    assert rc == 1
    assert "full tree" in captured.err
    assert "bare-except" in captured.out


def test_cli_select_runs_only_selected(tmp_path, capsys):
    p = tmp_path / "m.py"
    p.write_text("import os\n\ntry:\n    pass\nexcept:\n    pass\n")
    assert lint_main(["--select", "unused-import", str(p)]) == 1
    out = capsys.readouterr().out
    assert "unused-import" in out and "bare-except" not in out


# -- stats-cadence (ISSUE 15) ------------------------------------------

_STATS_BAD = """\
import numpy


class Step:
    def publish(self, outputs):
        stats = {k[5:]: v for k, v in outputs.items()
                 if k.startswith("stat/")}
        for layer, vec in stats.items():
            self.sink(layer, numpy.asarray(vec))   # per-step sync
            self.loss = float(vec[0])              # and another
"""

_STATS_GOOD = """\
import numpy


class Step:
    def _stats_due(self):
        self._tick += 1
        return self._tick % self.stats_interval == 0

    def publish(self, outputs):
        stats = {k[5:]: v for k, v in outputs.items()
                 if k.startswith("stat/")}
        if not self._stats_due():
            return
        for layer, vec in stats.items():
            self.sink(layer, numpy.asarray(vec))
"""


def test_stats_cadence_fires_on_ungated_materialization(tmp_path):
    """Satellite (ISSUE 15): a function handling "stat/"-keyed step
    outputs that materializes them (asarray + float) without ever
    consulting a stats_due gate fires once per materializer."""
    findings = lint_src(tmp_path, _STATS_BAD,
                        select=["stats-cadence"])
    assert set(rule_ids(findings)) == {"stats-cadence"}
    assert len(findings) == 2
    messages = " | ".join(f.message for f in findings)
    assert "'asarray'" in messages and "'float'" in messages
    assert "cadence" in findings[0].message


def test_stats_cadence_quiet_when_gated_and_on_sink(tmp_path):
    """The compliant shape — materialization behind a stats_due gate
    — is quiet; so is the observe_stats sink itself (every caller is
    forced through the gate) and pure key routing with no
    materializer."""
    assert lint_src(tmp_path, _STATS_GOOD,
                    select=["stats-cadence"]) == []
    sink = """\
import numpy


class Monitor:
    def observe_stats(self, layer_stats, step_index=None):
        for layer, vec in layer_stats.items():
            self.layers[layer] = float(numpy.asarray(vec)[0])
"""
    assert lint_src(tmp_path, sink, select=["stats-cadence"]) == []
    routing = """\
STAT_KEY_PREFIX = "stat/"


def take_stats(outputs):
    stats, rest = {}, {}
    for key, value in outputs.items():
        if key.startswith(STAT_KEY_PREFIX):
            stats[key[len(STAT_KEY_PREFIX):]] = value
        else:
            rest[key] = value
    return stats, rest
"""
    assert lint_src(tmp_path, routing,
                    select=["stats-cadence"]) == []


def test_stats_cadence_fires_on_sink_caller_and_pragma(tmp_path):
    """Calling the observe_stats sink marks a function stat-handling
    even without the string marker; the pragma escape works."""
    caller = """\
import numpy


class Step:
    def flush(self, vecs):
        host = [numpy.asarray(v) for v in vecs]
        self.monitor.observe_stats(dict(enumerate(host)))
"""
    findings = lint_src(tmp_path, caller, select=["stats-cadence"])
    assert rule_ids(findings) == ["stats-cadence"]
    pragma = """\
import numpy


class Step:
    def flush(self, vecs):
        host = [numpy.asarray(v) for v in vecs]  # zlint: disable=stats-cadence (one-shot postmortem dump, not a per-step path)
        self.monitor.observe_stats(dict(enumerate(host)))
"""
    assert lint_src(tmp_path, pragma,
                    select=["stats-cadence"]) == []


# -- taint rules (ISSUE 20) --------------------------------------------

_GEOM_BAD = """\
import numpy


class Proto:
    def handle(self, kind, payload):
        self.buf = numpy.zeros(payload["shape"])
"""

_GEOM_GOOD = """\
import numpy


class Proto:
    def handle(self, kind, payload):
        self.buf = numpy.zeros(
            self._validate_shape(payload["shape"]))

    def _validate_shape(self, shape):
        return [min(int(d), 64) for d in shape]
"""


def test_untrusted_geometry_fires_on_wire_shape(tmp_path):
    """A wire-handler payload sizing an allocation fires; routing it
    through a sanitizer-named bounder is quiet; a pragma'd site is
    quiet."""
    findings = lint_src(tmp_path, _GEOM_BAD,
                        select=["untrusted-geometry"])
    assert rule_ids(findings) == ["untrusted-geometry"]
    assert "wire" in findings[0].message
    assert lint_src(tmp_path, _GEOM_GOOD,
                    select=["untrusted-geometry"]) == []
    pragmad = _GEOM_BAD.replace(
        'payload["shape"])',
        'payload["shape"])  '
        '# zlint: disable=untrusted-geometry (test fixture)')
    assert lint_src(tmp_path, pragmad,
                    select=["untrusted-geometry"]) == []


def test_untrusted_geometry_crosses_calls(tmp_path):
    """Interprocedural: the handler hands its payload to a helper
    that allocates — the finding lands at the sink with the call
    chain in the message."""
    src = """\
import numpy


class Proto:
    def handle(self, kind, payload):
        self._apply(payload)

    def _apply(self, doc):
        self.buf = numpy.zeros(doc["shape"])
"""
    findings = lint_src(tmp_path, src,
                        select=["untrusted-geometry"])
    assert rule_ids(findings) == ["untrusted-geometry"]
    assert "via" in findings[0].message
    assert "handle" in findings[0].message


_CARD_BAD = """\
class Server:
    def __init__(self):
        self.stats = {}

    def handle(self, kind, payload):
        self.stats[kind] = payload
"""

_CARD_GOOD = """\
class Server:
    def __init__(self):
        self.stats = {}

    def handle(self, kind, payload):
        self.stats[self._resolve_kind(kind)] = payload

    def _resolve_kind(self, kind):
        return kind if kind in ("job", "update") else "other"
"""


def test_unbounded_cardinality_fires_on_wire_keyed_growth(tmp_path):
    findings = lint_src(tmp_path, _CARD_BAD,
                        select=["unbounded-cardinality"])
    assert rule_ids(findings) == ["unbounded-cardinality"]
    assert lint_src(tmp_path, _CARD_GOOD,
                    select=["unbounded-cardinality"]) == []
    pragmad = _CARD_BAD.replace(
        "self.stats[kind] = payload",
        "self.stats[kind] = payload  "
        "# zlint: disable=unbounded-cardinality (test fixture)")
    assert lint_src(tmp_path, pragmad,
                    select=["unbounded-cardinality"]) == []


def test_unbounded_cardinality_http_source_and_bounded_class(
        tmp_path):
    """The http taint kind (request.body) reaches the same sink; a
    container whose class is Bounded* by name is exempt."""
    src = """\
import json


class Frontend:
    def __init__(self):
        self.seen = {}

    def serve(self, request):
        doc = json.loads(request.body)
        self.seen[doc["name"]] = doc
"""
    findings = lint_src(tmp_path, src,
                        select=["unbounded-cardinality"])
    assert rule_ids(findings) == ["unbounded-cardinality"]
    assert "http" in findings[0].message
    bounded = src.replace("self.seen = {}",
                          "self.seen = BoundedDict(256)")
    assert lint_src(tmp_path, bounded,
                    select=["unbounded-cardinality"]) == []


_DESER_BAD = """\
import pickle


class Proto:
    def handle(self, kind, payload):
        return pickle.loads(payload)
"""

_DESER_GOOD = """\
import hmac
import pickle


class Proto:
    def handle(self, kind, payload, tag):
        if not hmac.compare_digest(self._sign(payload), tag):
            raise ValueError("bad tag")
        return pickle.loads(payload)
"""


def test_unsafe_deserialize_fires_without_hmac(tmp_path):
    findings = lint_src(tmp_path, _DESER_BAD,
                        select=["unsafe-deserialize"])
    assert rule_ids(findings) == ["unsafe-deserialize"]
    assert lint_src(tmp_path, _DESER_GOOD,
                    select=["unsafe-deserialize"]) == []
    pragmad = _DESER_BAD.replace(
        "return pickle.loads(payload)",
        "return pickle.loads(payload)  "
        "# zlint: disable=unsafe-deserialize (test fixture)")
    assert lint_src(tmp_path, pragmad,
                    select=["unsafe-deserialize"]) == []


_PATH_BAD = """\
class Store:
    def handle(self, kind, payload):
        with open(payload["path"]) as f:
            return f.read()
"""

_PATH_GOOD = """\
class Store:
    def handle(self, kind, payload):
        with open(self._resolve_path(payload["path"])) as f:
            return f.read()

    def _resolve_path(self, name):
        return self.root + "/" + name.rsplit("/", 1)[-1]
"""


def test_untrusted_path_fires_on_wire_filename(tmp_path):
    findings = lint_src(tmp_path, _PATH_BAD,
                        select=["untrusted-path"])
    assert rule_ids(findings) == ["untrusted-path"]
    assert lint_src(tmp_path, _PATH_GOOD,
                    select=["untrusted-path"]) == []
    pragmad = _PATH_BAD.replace(
        'with open(payload["path"]) as f:',
        'with open(payload["path"]) as f:  '
        '# zlint: disable=untrusted-path (test fixture)')
    assert lint_src(tmp_path, pragmad,
                    select=["untrusted-path"]) == []


def test_sanitizer_annotation_kills_taint(tmp_path):
    """The ``# zlint: sanitizer`` recipe: a bounded tenant-table
    lookup that is NOT sanitizer-named still cleans what flows
    through it — the sanitizer-kills-taint pin."""
    src = """\
import numpy


def bounded_dims(doc):  # zlint: sanitizer (schema-checked upstream)
    return doc["rows"], doc["cols"]


class Proto:
    def handle(self, kind, payload):
        self.buf = numpy.zeros(bounded_dims(payload))
"""
    assert lint_src(tmp_path, src,
                    select=["untrusted-geometry"]) == []
    # the same flow WITHOUT the annotation fires — the pin is
    # falsifiable
    unannotated = src.replace(
        "  # zlint: sanitizer (schema-checked upstream)", "")
    findings = lint_src(tmp_path, unannotated,
                        select=["untrusted-geometry"])
    assert rule_ids(findings) == ["untrusted-geometry"]
    # the engine's bounded-lookup shape needs no annotation at all:
    # .get() off an untainted module table returns the TABLE's data
    table = """\
import numpy

TABLE = {"small": (4, 4), "big": (64, 64)}


class Proto:
    def handle(self, kind, payload):
        self.buf = numpy.zeros(TABLE.get(payload["profile"],
                                         (4, 4)))
"""
    assert lint_src(tmp_path, table,
                    select=["untrusted-geometry"]) == []


def test_range_guard_kills_taint(tmp_path):
    """An explicit comparison guard is a sanitizer: after the
    programmer bounded the value, downstream sinks stay quiet."""
    src = """\
import numpy


class Proto:
    def handle(self, kind, payload):
        n = payload["n"]
        if n > 4096:
            raise ValueError("too big")
        self.buf = numpy.zeros(n)
"""
    assert lint_src(tmp_path, src,
                    select=["untrusted-geometry"]) == []


# -- incremental analysis cache (ISSUE 20) -----------------------------


def _write_tree(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)


def _run_cached(tmp_path, cache_dir):
    from veles.analysis.cache import AnalysisCache
    stats = []
    findings = analyze_paths([str(tmp_path / "pkg")],
                             base=str(tmp_path),
                             cache=AnalysisCache(str(cache_dir)),
                             stats=stats)
    return findings, {row["rule"]: row for row in stats}


def test_cache_reuses_and_reanalyzes_only_dependents(tmp_path):
    """THE cache-correctness pin: a warm run is all-cached with
    byte-identical findings; editing one module re-analyzes only the
    modules whose import closure contains it — and the findings
    still match an uncached run byte for byte."""
    _write_tree(tmp_path, {
        "pkg/a.py": "import os\n\nfrom pkg import b\n\n\n"
                    "def use():\n    return b.helper()\n",
        "pkg/b.py": "def helper():\n    return 1\n",
        "pkg/c.py": "X = 1\n",
    })
    cache_dir = tmp_path / "zc"
    cold, stats_cold = _run_cached(tmp_path, cache_dir)
    assert stats_cold["unused-import"]["fresh_modules"] == 3
    # the planted finding: a.py's unused os import
    assert [f.rule for f in cold] == ["unused-import"]
    warm, stats_warm = _run_cached(tmp_path, cache_dir)
    assert stats_warm["unused-import"]["fresh_modules"] == 0
    assert stats_warm["unused-import"]["cached_modules"] == 3
    assert json.dumps([f.as_dict() for f in warm]) \
        == json.dumps([f.as_dict() for f in cold])
    # edit b.py: a.py (imports b) and b.py re-analyze, c.py answers
    # from cache; a project-scope rule re-runs over everything
    (tmp_path / "pkg/b.py").write_text(
        "def helper():\n    return 2\n")
    edited, stats_edit = _run_cached(tmp_path, cache_dir)
    assert stats_edit["unused-import"]["fresh_modules"] == 2
    assert stats_edit["unused-import"]["cached_modules"] == 1
    assert stats_edit["untrusted-geometry"]["fresh_modules"] == 3
    uncached = analyze_paths([str(tmp_path / "pkg")],
                             base=str(tmp_path))
    assert json.dumps([f.as_dict() for f in edited]) \
        == json.dumps([f.as_dict() for f in uncached])


def test_cache_invalidates_on_import_graph_change(tmp_path):
    """Adding an import EDGE re-keys the importer: before the edge,
    editing b leaves a cached; after a.py gains ``import b``, an edit
    to b.py alone re-analyzes a.py too."""
    _write_tree(tmp_path, {
        "pkg/a.py": "def use():\n    return 1\n",
        "pkg/b.py": "def helper():\n    return 1\n",
    })
    cache_dir = tmp_path / "zc"
    _run_cached(tmp_path, cache_dir)
    (tmp_path / "pkg/b.py").write_text(
        "def helper():\n    return 2\n")
    _, stats = _run_cached(tmp_path, cache_dir)
    # no edge yet: only b itself re-analyzes
    assert stats["unused-import"]["fresh_modules"] == 1
    (tmp_path / "pkg/a.py").write_text(
        "from pkg import b\n\n\ndef use():\n    return b.helper()\n")
    _run_cached(tmp_path, cache_dir)            # warm the new graph
    (tmp_path / "pkg/b.py").write_text(
        "def helper():\n    return 3\n")
    _, stats = _run_cached(tmp_path, cache_dir)
    # the edge exists: b's edit invalidates a's closure key as well
    assert stats["unused-import"]["fresh_modules"] == 2


def test_cache_pragma_edit_rekeys_the_module(tmp_path):
    """Findings are stored post-pragma-filter — sound only because a
    pragma edit changes the module's content hash and therefore its
    key."""
    _write_tree(tmp_path, {"pkg/a.py": "import os\n"})
    cache_dir = tmp_path / "zc"
    cold, _ = _run_cached(tmp_path, cache_dir)
    assert [f.rule for f in cold] == ["unused-import"]
    (tmp_path / "pkg/a.py").write_text(
        "import os  # zlint: disable=unused-import (test)\n")
    warm, _ = _run_cached(tmp_path, cache_dir)
    assert warm == []


def test_cli_cache_and_stats(tmp_path, capsys):
    """--cache + --stats: the text table reports fresh/cached module
    counts, --json wraps {findings, stats}, and a warm --format json
    run (no --stats) is byte-identical to the cold one."""
    p = tmp_path / "m.py"
    p.write_text("import os\n\ntry:\n    pass\nexcept:\n    pass\n")
    cache_dir = str(tmp_path / "zc")
    rc = lint_main([str(p), "--cache", cache_dir, "--stats"])
    out_cold = capsys.readouterr().out
    assert rc == 1
    assert "fresh" in out_cold and "cached" in out_cold
    rc = lint_main([str(p), "--cache", cache_dir, "--stats",
                    "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert {f["rule"] for f in doc["findings"]} \
        == {"unused-import", "bare-except"}
    by_rule = {r["rule"]: r for r in doc["stats"]}
    assert by_rule["bare-except"]["cached_modules"] == 1
    assert by_rule["bare-except"]["fresh_modules"] == 0
    # cold vs warm byte-identity of the findings document
    lint_main([str(p), "--format", "json"])
    plain = capsys.readouterr().out
    lint_main([str(p), "--cache", cache_dir, "--format", "json"])
    warm = capsys.readouterr().out
    assert warm == plain


def test_cli_precommit_invocation(tmp_path, capsys):
    """The documented pre-commit hook line: ``velescli lint
    --changed-only --cache .zlint-cache --format sarif``. With a
    cache the full tree is kept (cross-file context intact) and the
    SARIF document is byte-identical to an uncached full run."""
    _git(tmp_path, "init", "-q")
    a = tmp_path / "a.py"
    a.write_text("X = 1\n")
    b = tmp_path / "b.py"
    b.write_text("Y = 2\n")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "seed")
    b.write_text("try:\n    pass\nexcept:\n    pass\n")
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        rc = lint_main([str(tmp_path), "--changed-only", "--cache",
                        str(tmp_path / ".zlint-cache"), "--format",
                        "sarif", "--select", "bare-except"])
        sarif_warm = capsys.readouterr().out
        rc_full = lint_main([str(tmp_path), "--format", "sarif",
                             "--select", "bare-except"])
        sarif_full = capsys.readouterr().out
    finally:
        os.chdir(cwd)
    assert rc == 1 and rc_full == 1
    assert sarif_warm == sarif_full
    doc = json.loads(sarif_warm)
    assert doc["runs"][0]["results"][0]["ruleId"] == "bare-except"


# -- the permanent gate ------------------------------------------------


def test_repo_wide_zero_findings_gate():
    """THE gate: the whole veles package — and bench.py, which
    builds samples from target-advertised geometry — stays at zero
    findings, the four taint rules included.

    If this fails, `velescli lint veles bench.py` reproduces it
    locally with file:line + a fix hint per finding. Fix the code,
    or — for a documented false positive / deliberate design — add
    `# zlint: disable=RULE (reason)` on the flagged line."""
    import veles
    pkg = os.path.dirname(os.path.abspath(veles.__file__))
    repo = os.path.dirname(pkg)
    findings = analyze_paths([pkg, os.path.join(repo, "bench.py")],
                             base=repo)
    assert findings == [], (
        "zlint found %d violation(s) in veles/ + bench.py:\n%s"
        % (len(findings), "\n".join(f.render() for f in findings)))


def test_gate_would_catch_a_regression(tmp_path):
    """The gate is falsifiable: a rule violation planted in a copy of
    a real module shape IS caught (guards against the analyzer
    silently skipping the package)."""
    src = """\
import threading


class Worker(Unit):
    def __init__(self):
        self._lock = threading.Lock()

    def run(self):
        self.epoch = getattr(self, "epoch", 0) + 1
"""
    findings = lint_src(tmp_path, src, select=["checkpoint-state"])
    assert findings, "planted violation must be caught"


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
