"""Interactive shell unit + forge client (SURVEY.md §2.7 rows 6-7)."""

import json
import os
import subprocess
import sys

import numpy
import pytest

import veles.prng as prng
from veles.config import root

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_shell_commands_run_per_epoch():
    prng.seed_all(808)
    from veles.interaction import Shell
    from veles.znicz_tpu.models import mnist
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    root.mnist.loader.update(
        {"n_train": 200, "n_valid": 80, "minibatch_size": 40})
    root.mnist.decision.max_epochs = 2
    try:
        wf = mnist.create_workflow(name="ShellWF")
        sh = Shell(wf, name="shell", commands=[
            "wf.shell_probe = wf.decision.epoch_number",
            "assert loader is wf.loader",
        ])
        sh.link_from(wf.decision)
        sh.gate_skip = ~wf.decision.epoch_ended
        wf._end_point_last()
        wf.initialize(device="numpy")
        wf.run()
    finally:
        root.mnist.loader.update(saved)
        root.mnist.decision.max_epochs = 5
    assert sh.activations == 2
    assert all(exc is None for _, exc in sh.results)
    # decision had already rolled the epoch counter when the shell ran
    assert wf.shell_probe == 2


def test_shell_stop_ends_run():
    prng.seed_all(809)
    from veles.interaction import Shell
    from veles.znicz_tpu.models import mnist
    saved = {k: root.mnist.loader.get(k)
             for k in ("n_train", "n_valid", "minibatch_size")}
    root.mnist.loader.update(
        {"n_train": 200, "n_valid": 80, "minibatch_size": 40})
    root.mnist.decision.max_epochs = 50
    try:
        wf = mnist.create_workflow(name="ShellStop")
        sh = Shell(wf, name="shell", commands=["stop()"])
        sh.link_from(wf.decision)
        sh.gate_skip = ~wf.decision.epoch_ended
        wf._end_point_last()
        wf.initialize(device="numpy")
        wf.run()
    finally:
        root.mnist.loader.update(saved)
        root.mnist.decision.max_epochs = 5
    # stopped after the first epoch, far short of max_epochs
    assert len(wf.decision.history) <= 2


# -- forge ------------------------------------------------------------


def test_forge_roundtrip(tmp_path):
    from veles import forge_client as forge
    store = str(tmp_path / "store")
    art = tmp_path / "weights.npy"
    numpy.save(art, numpy.arange(6.0))
    pkg = forge.upload("mlp", [str(art)], store=store, version="1",
                       description="test model")
    assert os.path.exists(pkg)
    pkgs = forge.list_packages(store)
    assert [m["name"] for m in pkgs] == ["mlp"]
    dest = str(tmp_path / "out")
    meta = forge.fetch("mlp", dest, store=store)
    assert meta["version"] == "1"
    got = numpy.load(os.path.join(dest, "weights.npy"))
    numpy.testing.assert_array_equal(got, numpy.arange(6.0))


def test_forge_versions_and_missing(tmp_path):
    from veles import forge_client as forge
    store = str(tmp_path / "store")
    art = tmp_path / "a.npy"
    numpy.save(art, numpy.zeros(2))
    forge.upload("m", [str(art)], store=store, version="9")
    numpy.save(art, numpy.ones(2))
    forge.upload("m", [str(art)], store=store, version="10")
    dest = str(tmp_path / "o")
    meta = forge.fetch("m", dest, store=store)
    # NUMERIC newest wins: 10 > 9 (not lexicographic)
    assert meta["version"] == "10"
    numpy.testing.assert_array_equal(
        numpy.load(os.path.join(dest, "a.npy")), numpy.ones(2))
    with pytest.raises(FileNotFoundError):
        forge.fetch("nope", dest, store=store)


def test_forge_rejects_unsafe_names(tmp_path):
    from veles import forge_client as forge
    art = tmp_path / "a.npy"
    numpy.save(art, numpy.zeros(1))
    store = str(tmp_path / "store")
    for bad in ("../escape", "a/b", ".hidden"):
        with pytest.raises(ValueError, match="invalid package name"):
            forge.upload(bad, [str(art)], store=store, version="1")
    with pytest.raises(ValueError, match="invalid version"):
        forge.upload("ok", [str(art)], store=store, version="1/2")


def test_shell_records_failures():
    """Failing commands are captured, not swallowed (and never kill
    training)."""
    from veles.interaction import Shell
    from veles.workflow import Workflow
    wf = Workflow(None, name="ShErr")
    sh = Shell(wf, name="shell",
               commands=["x = 1", "raise ValueError('boom')", "y = x"])
    sh.run()
    assert sh.results[0][1] is None
    assert isinstance(sh.results[1][1], ValueError)
    assert sh.results[2][1] is None   # later commands still ran


def test_forge_cli(tmp_path):
    store = str(tmp_path / "store")
    art = str(tmp_path / "w.npy")
    numpy.save(art, numpy.arange(3.0))
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "veles.forge_client", "--store", store,
         "upload", "demo", art, "--version", "7"],
        capture_output=True, text=True, env=env, check=True)
    assert r.stdout.strip().endswith("demo-7.forge.tar.gz")
    r = subprocess.run(
        [sys.executable, "-m", "veles.forge_client", "--store", store,
         "list"], capture_output=True, text=True, env=env, check=True)
    assert "demo" in r.stdout
    dest = str(tmp_path / "fetched")
    r = subprocess.run(
        [sys.executable, "-m", "veles.forge_client", "--store", store,
         "fetch", "demo", dest], capture_output=True, text=True,
        env=env, check=True)
    assert json.loads(r.stdout)["version"] == "7"
    assert os.path.exists(os.path.join(dest, "w.npy"))
