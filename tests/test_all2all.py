"""Per-op golden tests for the dense stack, reference-style (SURVEY.md
§4 "Unit tests"): numpy_run is the oracle; the traced xla path must
allclose it. jax.grad serves as a second oracle for the hand-written
backward (SURVEY.md §7 "Hard parts": autodiff only in tests)."""

import numpy
import pytest

import veles.prng as prng
from veles.backends import XLADevice
from veles.memory import Array
from veles.accelerated_units import AcceleratedUnit, StepCompiler
from veles.workflow import Workflow
from veles.znicz_tpu.ops.all2all import (
    All2All, All2AllTanh, All2AllRELU, All2AllStrictRELU,
    All2AllSigmoid, All2AllSoftmax)
from veles.znicz_tpu.nn_units import gradient_unit_for


class FeedUnit(AcceleratedUnit):
    """Minimal producer holding a minibatch Array."""

    def __init__(self, workflow, data):
        super().__init__(workflow, name="feed")
        self.minibatch_data = Array(data)

    def numpy_run(self):
        pass

    def xla_run(self, ctx):
        pass


def make_pair(cls, batch=8, n_in=20, n_out=12, transposed=False):
    prng.seed_all(42)
    wf = Workflow(None, name="wf")
    gen = prng.get("t")
    x = gen.normal(0, 1.0, (batch, n_in))
    feed = FeedUnit(wf, x)
    fwd = cls(wf, output_sample_shape=n_out,
              weights_transposed=transposed)
    fwd.link_attrs(feed, ("input", "minibatch_data"))
    fwd.initialize(device=None)
    return wf, feed, fwd, x


@pytest.mark.parametrize("cls", [
    All2All, All2AllTanh, All2AllRELU, All2AllStrictRELU,
    All2AllSigmoid, All2AllSoftmax])
def test_forward_numpy_vs_xla(cls):
    wf, feed, fwd, x = make_pair(cls)
    fwd.numpy_run()
    golden = numpy.array(fwd.output.mem)

    dev = XLADevice(platform="cpu")
    comp = StepCompiler([fwd], dev)
    import jax
    from veles.accelerated_units import FlowContext

    def fn(p, xv):
        ctx = FlowContext(comp, p, {}, {}, jax.random.PRNGKey(0), False)
        ctx.set(feed, "minibatch_data", xv)
        fwd.xla_run(ctx)
        return ctx.get(fwd, "output")

    y = jax.jit(fn)(comp.gather_params(), x)
    assert numpy.allclose(numpy.asarray(y), golden, atol=2e-5), cls


@pytest.mark.parametrize("cls,transposed", [
    (All2All, False), (All2AllTanh, False), (All2AllTanh, True),
    (All2AllRELU, False), (All2AllSigmoid, False)])
def test_gd_matches_jax_grad(cls, transposed):
    """Hand-written backward vs jax.grad on an L = sum(err_output * y)
    surrogate (so dL/dy == err_output)."""
    import jax
    import jax.numpy as jnp

    wf, feed, fwd, x = make_pair(cls, transposed=transposed)
    gd_cls = gradient_unit_for(cls)
    gd = gd_cls(wf, learning_rate=0.0)  # lr=0: only check gradients
    gd.setup_forward(fwd)
    gen = prng.get("t2")
    err_out = gen.normal(0, 1.0, (x.shape[0], fwd.neurons))
    gd.err_output = Array(err_out)
    fwd.numpy_run()
    gd.initialize(device=None)
    w0 = numpy.array(fwd.weights.mem)
    b0 = numpy.array(fwd.bias.mem)
    gd.numpy_run()
    err_input = numpy.array(gd.err_input.mem)

    # jax.grad oracle over the surrogate loss
    from veles.znicz_tpu.ops import activations as A

    def loss(w, b, xv):
        v = xv @ (w.T if transposed else w) + b
        y = A.ACTIVATIONS[cls.ACTIVATION][0](jnp, v)
        return jnp.sum(jnp.asarray(err_out) * y)

    gw, gb, gx = jax.grad(loss, argnums=(0, 1, 2))(
        jnp.asarray(w0), jnp.asarray(b0), jnp.asarray(x))
    assert numpy.allclose(err_input, numpy.asarray(gx), atol=1e-4)

    # now check the actual weight update applies -lr * grad
    gd.learning_rate = 0.5
    gd.learning_rate_bias = 0.5
    fwd.weights.mem = w0.copy()
    fwd.bias.mem = b0.copy()
    gd.vel_weights.mem = numpy.zeros_like(w0)
    gd.vel_bias.mem = numpy.zeros_like(b0)
    gd.numpy_run()
    assert numpy.allclose(fwd.weights.mem, w0 - 0.5 * numpy.asarray(gw),
                          atol=1e-4)
    assert numpy.allclose(fwd.bias.mem, b0 - 0.5 * numpy.asarray(gb),
                          atol=1e-4)


def test_gd_xla_matches_numpy():
    """Full train-step parity: numpy unit-by-unit vs one fused XLA step."""
    import jax

    wf, feed, fwd, x = make_pair(All2AllTanh)
    gd = gradient_unit_for(All2AllTanh)(
        wf, learning_rate=0.1, gradient_moment=0.9, weights_decay=0.01)
    gd.setup_forward(fwd)
    gen = prng.get("t3")
    err_out = gen.normal(0, 1.0, (x.shape[0], fwd.neurons))
    gd.err_output = Array(err_out)
    fwd.numpy_run()
    gd.initialize(device=None)

    dev = XLADevice(platform="cpu")
    comp = StepCompiler([fwd, gd], dev)
    params0 = comp.gather_params()
    state0 = comp.gather_state()
    hyper = {gd.name: gd.hyperparams()}
    step = comp.build_step({"data": (feed, "minibatch_data")},
                           train=True)
    params1, state1, _ = step(params0, state0, {"data": x}, hyper,
                              jax.random.PRNGKey(0))

    # oracle
    gd.numpy_run()
    assert numpy.allclose(numpy.asarray(params1[fwd.name]["weights"]),
                          fwd.weights.mem, atol=2e-4)
    assert numpy.allclose(numpy.asarray(params1[fwd.name]["bias"]),
                          fwd.bias.mem, atol=2e-4)
    assert numpy.allclose(numpy.asarray(state1[gd.name]["vel_weights"]),
                          gd.vel_weights.mem, atol=2e-4)


def test_gradient_accumulation_parity():
    """accumulate_gradient=2: one update every 2 minibatches, identical
    between numpy oracle and the compiled step."""
    import jax

    wf, feed, fwd, x = make_pair(All2AllTanh)
    gd = gradient_unit_for(All2AllTanh)(
        wf, learning_rate=0.1, accumulate_gradient=2)
    gd.setup_forward(fwd)
    gen = prng.get("t4")
    errs = [gen.normal(0, 1.0, (x.shape[0], fwd.neurons))
            for _ in range(2)]
    gd.err_output = Array(errs[0])
    fwd.numpy_run()
    gd.initialize(device=None)
    w0 = numpy.array(fwd.weights.mem)

    dev = XLADevice(platform="cpu")
    comp = StepCompiler([fwd, gd], dev)
    params = comp.gather_params()
    state = comp.gather_state()
    step = comp.build_step({"data": (feed, "minibatch_data"),
                            "err": (gd, "err_output")}, train=True)
    hyper = {gd.name: gd.hyperparams()}
    for e in errs:
        params, state, _ = step(params, state,
                                {"data": x, "err": e}, hyper,
                                jax.random.PRNGKey(0))

    for e in errs:
        gd.err_output.mem = e
        fwd.numpy_run()
        gd.numpy_run()

    # after step 1 no change; after step 2 both applied the summed grad
    assert not numpy.allclose(fwd.weights.mem, w0)
    assert numpy.allclose(numpy.asarray(params[fwd.name]["weights"]),
                          fwd.weights.mem, atol=2e-4)
    assert int(gd.acc_count.map_read().mem) == 0
