"""CIFAR-10 functional test (BASELINE config #2; SURVEY.md §4): the
convnet sample trains on both backends with matching accuracy."""

import pytest

import veles.prng as prng
from veles.config import root


def build_and_run(backend, name):
    prng.seed_all(2024)
    from veles.znicz_tpu.models import cifar10
    root.cifar.loader.n_train = 600
    root.cifar.loader.n_valid = 200
    root.cifar.loader.minibatch_size = 50
    root.cifar.decision.max_epochs = 3
    for layer in root.cifar.layers:
        if "<-" in layer:
            layer["<-"]["learning_rate"] = 0.01
            layer["<-"]["gradient_moment"] = 0.5
    wf = cifar10.create_workflow(name=name)
    wf.initialize(device=backend)
    wf.run()
    return wf


@pytest.fixture(scope="module")
def numpy_wf():
    return build_and_run("numpy", "CifarNumpy")


def test_cifar_converges(numpy_wf):
    hist = [h["validation"]["metric"]
            for h in numpy_wf.decision.history]
    assert hist[-1] < hist[0], hist
    assert hist[-1] < 0.55, hist


def test_cifar_xla_matches_numpy(numpy_wf):
    wf = build_and_run("cpu", "CifarXLA")
    err_np = numpy_wf.decision.history[-1]["validation"]["metric"]
    err_x = wf.decision.history[-1]["validation"]["metric"]
    assert abs(err_np - err_x) < 0.08, (err_np, err_x)
