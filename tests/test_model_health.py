"""Model-health plane (ISSUE 15, veles/model_health.py): in-graph
training-dynamics telemetry, the divergence detector + SLOs, verified
checkpoints, the rollback actuators, and the fleet surfaces.

Everything deterministic: detector tests feed observations directly,
the master-side tests drive server.handle() synchronously (no socket
luck), and the E2E runs real sockets with ONE planned poisoned update.
"""

import json
import threading
import time
import urllib.request

import numpy
import pytest

import veles.prng as prng
from veles import health, model_health, telemetry
from veles.chaos import poison_update
from veles.client import SlaveClient
from veles.config import root
from veles.distributable import DistributionRegistry
from veles.loader.base import CLASS_TRAIN
from veles.model_health import (ModelHealthMonitor, WeightGuard,
                                install_model_slos, take_stats)
from veles.server import MasterServer
from veles.snapshotter import (FileSnapshotStore, resolve_auto,
                               scan_checkpoints, write_checkpoint)
from tests.test_chaos import run_iteration, sequential_reference
from tests.test_service import make_wf


# -- the detector (pure observations) ----------------------------------


def test_take_stats_routes_stat_keys():
    outs = {"loss": 1.0, "stat/gd1": [1, 2, 3, 0], "n_err": 2}
    stats, rest = take_stats(outs)
    assert stats == {"gd1": [1, 2, 3, 0]}
    assert rest == {"loss": 1.0, "n_err": 2}


def test_nonfinite_stats_diverge_then_recover():
    mon = ModelHealthMonitor(recover_after=2)
    mon.observe_stats({"fc": numpy.array([1.0, 5.0, 0.01, 0.0])})
    assert mon.verdict_state()[0] == "healthy"
    mon.observe_stats({"fc": numpy.array([1.0, 5.0, 0.01, 3.0])})
    verdict, reasons = mon.verdict_state()
    assert verdict == "diverged"
    assert any("nonfinite:fc" in r for r in reasons)
    doc = mon.snapshot()
    assert doc["nonfinite_total"] == 3
    assert doc["layers"]["fc"]["nonfinite"] == 3.0
    # recovery: recover_after clean observations flip it back
    mon.observe_stats({"fc": numpy.array([1.0, 5.0, 0.01, 0.0])})
    assert mon.verdict_state()[0] == "diverged"
    mon.observe_stats({"fc": numpy.array([1.0, 5.0, 0.01, 0.0])})
    assert mon.verdict_state()[0] == "healthy"


def test_nonfinite_norm_counts_even_when_count_missed():
    """inf^2 overflow can turn the in-trace count into NaN/inf norms
    with count 0 — a non-finite norm still reads as >= 1 bad value."""
    mon = ModelHealthMonitor()
    mon.observe_stats(
        {"fc": numpy.array([numpy.nan, 5.0, 0.01, 0.0])})
    assert mon.verdict_state()[0] == "diverged"
    assert mon.snapshot()["nonfinite_total"] >= 1


def test_loss_spike_zscore_suspect_and_diverged():
    mon = ModelHealthMonitor(suspect_z=4.0, diverged_z=8.0,
                             ewma_alpha=0.2, recover_after=3)
    rng = numpy.random.Generator(numpy.random.PCG64(7))
    for i in range(20):
        mon.observe_loss(1.0 + 0.01 * rng.standard_normal(), epoch=i)
    assert mon.verdict_state()[0] == "healthy"
    mon.observe_loss(1.3, epoch=20)        # far above EWMA noise
    verdict, reasons = mon.verdict_state()
    assert verdict == "diverged"
    assert any("loss_spike" in r for r in reasons)
    assert mon.snapshot()["loss_zscore"] > 8.0


def test_loss_blowup_on_second_observation_is_caught():
    """Review fix: with one prior loss the variance is still 0 — the
    relative-jump fallback (loss > 4x baseline) must catch a finite
    blow-up instead of forcing z=0, and the spike must NOT fold into
    the EWMA baseline (later z-scores stay sensitive)."""
    mon = ModelHealthMonitor()
    mon.observe_loss(0.5, epoch=0)
    mon.observe_loss(1.0e6, epoch=1)
    verdict, reasons = mon.verdict_state()
    assert verdict == "diverged"
    assert any("loss_spike" in r for r in reasons)
    assert mon.snapshot()["loss_ewma"] == pytest.approx(0.5)


def test_nonfinite_loss_diverges_immediately():
    mon = ModelHealthMonitor()
    mon.observe_loss(float("nan"), epoch=0)
    verdict, reasons = mon.verdict_state()
    assert verdict == "diverged" and "loss_nonfinite" in reasons


def test_grad_explosion_flags_suspect():
    mon = ModelHealthMonitor(explosion_factor=10.0)
    for _ in range(5):
        mon.observe_stats({"fc": numpy.array([1.0, 5.0, 0.01, 0.0])})
    mon.observe_stats({"fc": numpy.array([50.0, 5.0, 0.01, 0.0])})
    verdict, reasons = mon.verdict_state()
    assert verdict == "suspect"
    assert any("grad_explosion:fc" in r for r in reasons)


def test_clean_wire_notes_do_not_clear_a_diverged_latch():
    """A poisoned merge is followed by the SAME update frame's other
    units reporting 0 — clean notes are TIME-paced (at most one
    healthy observation per wire_recovery_interval), so a burst of
    per-unit notes — however many units the model has — can never
    re-earn healthy before the ring samples the spike or the guard
    ticks."""
    mon = ModelHealthMonitor(recover_after=2)
    mon.note_wire_nonfinite("gd2", 4, slave=7)
    verdict, reasons = mon.verdict_state()
    assert verdict == "diverged"
    assert any("slave 7" in r for r in reasons)
    for _ in range(100):                    # a wide model's frame
        mon.note_wire_nonfinite("gd1", 0)
    assert mon.verdict_state()[0] == "diverged"
    # once the pacing interval elapses, clean notes recover
    mon.wire_recovery_interval = 0.0
    for _ in range(3):
        mon.note_wire_nonfinite("gd1", 0)
    assert mon.verdict_state()[0] == "healthy"


def test_absorb_slave_republishes_and_folds_verdict():
    mon = ModelHealthMonitor()
    mon.absorb_slave({"loss": 0.5, "verdict": "healthy",
                      "layers": {"fc": {"grad_norm": 1.5,
                                        "weight_norm": 4.0,
                                        "update_ratio": 0.01,
                                        "nonfinite": 0}}}, 3)
    assert mon.verdict_state()[0] == "healthy"
    assert "3" in mon.snapshot()["slaves"]
    reg = telemetry.get_registry()
    fam = reg.gauge("veles_model_grad_norm")
    values = {items: child.value for items, child in fam.children()}
    assert values[(("layer", "fc"), ("slave", "3"))] == 1.5
    # a slave that judged ITSELF diverged flips the master's verdict
    mon.absorb_slave({"loss": 9.9, "verdict": "diverged",
                      "layers": {}}, 4)
    verdict, reasons = mon.verdict_state()
    assert verdict == "diverged"
    assert any("slave_diverged:4" in r for r in reasons)


def test_healthy_slave_summaries_do_not_clear_diverged_latch():
    """Review fix: with NaN merged into the canonical weights, the
    OTHER slaves' routine healthy summaries keep arriving — they must
    not advance the healthy streak and re-stamp checkpoints healthy
    within seconds."""
    mon = ModelHealthMonitor(recover_after=2)
    mon.note_wire_nonfinite("gd", 3, slave=1)
    assert mon.verdict_state()[0] == "diverged"
    for _ in range(10):
        mon.absorb_slave({"loss": 0.4, "verdict": "healthy",
                          "layers": {}}, 2)
    assert mon.verdict_state()[0] == "diverged"


def test_weight_guard_does_not_stash_while_suspect():
    """Review fix: a finite blow-up flags suspect before the loss
    z-score confirms diverged — the guard must keep the PRE-spike
    stash through that window, not refresh onto spiked weights."""
    master_wf = make_wf("MHGuardSus", max_epochs=None)
    master_wf.decision.max_epochs = 2
    guard = WeightGuard(master_wf, stash_interval=1)
    guard.tick()                            # healthy -> stash armed
    w_good = numpy.array(
        master_wf.forwards[0].weights.map_read().mem)
    mon = model_health.get_model_monitor()
    # grad explosion: suspect
    for _ in range(4):
        mon.observe_stats({"fc": numpy.array([1.0, 5.0, 0.01, 0.0])})
    mon.observe_stats({"fc": numpy.array([99.0, 5.0, 0.01, 0.0])})
    assert mon.verdict_state()[0] == "suspect"
    # weights drift while suspect; guard ticks must NOT re-stash
    master_wf.forwards[0].weights.map_write().mem += 100.0
    guard.tick()
    mon.note_wire_nonfinite("fc", 1)        # now confirmed diverged
    assert guard.tick()                     # -> restore
    numpy.testing.assert_array_equal(
        master_wf.forwards[0].weights.map_read().mem, w_good)


def test_disabled_plane_never_judges():
    """Review fix: --model-stats off stands the WHOLE plane down — a
    loss spike or wire NaN must not flip the verdict (and thereby
    stamp checkpoints diverged / skip them on resume) while the
    operator turned the observability off."""
    mon = ModelHealthMonitor()
    mon.enabled = False
    mon.observe_loss(float("nan"), epoch=0)
    mon.note_wire_nonfinite("fc", 9)
    assert mon.verdict_state() == ("healthy", [])
    assert mon.snapshot()["loss"] is not None   # gauges still record
    # the MANIFEST stamp must not claim positive health a blind run
    # never established ("unknown" blobs still resume/serve — only
    # "diverged" is skipped)
    assert mon.manifest_stamp()["verdict"] == "unknown"


def test_render_survives_garbled_model_doc():
    """Review fix: a version-skewed /debug/model doc (non-numeric
    loss/rollbacks) degrades the row, never crashes the render."""
    from veles.fleet import render_snapshot
    row = {"url": "http://x:1", "reachable": True, "ready": True,
           "model": {"verdict": "diverged", "loss": "oops",
                     "rollbacks": "many", "layers": {"fc": "bad"}}}
    snap = {"ts": 0.0, "targets": [row],
            "fleet": {"targets": 1, "reachable": 1, "ready": 1,
                      "slaves": 0, "firing_slos": [],
                      "degraded": []}}
    out = render_snapshot(snap)
    assert "verdict diverged" in out


def test_serving_drift_gauges():
    mon = ModelHealthMonitor()
    # already a distribution: rows sum to 1
    probs = numpy.array([[0.8, 0.1, 0.1], [0.6, 0.3, 0.1]])
    mon.observe_serving("mnist", probs)
    drift = mon.snapshot()["serving"]["mnist"]
    assert 0.0 < drift["entropy"] < numpy.log(3.0) + 1e-9
    assert drift["top1_margin"] == pytest.approx(
        numpy.mean([0.7, 0.3]), abs=1e-6)
    # logits get softmaxed first; 1-D / scalar outputs are ignored
    mon.observe_serving("lm", numpy.array([[5.0, 1.0, 0.0]]))
    assert mon.snapshot()["serving"]["lm"]["top1_margin"] > 0.9
    mon.observe_serving("reg", numpy.array([1.0, 2.0]))
    assert "reg" not in mon.snapshot()["serving"]
    reg = telemetry.get_registry()
    assert reg.counter_total("veles_serving_logit_entropy") > 0


# -- SLO wiring --------------------------------------------------------


def test_model_slos_fire_on_nonfinite_and_flip_readyz():
    """Acceptance piece: one bad ring sample fires model_nonfinite
    within a tick (= an evaluation tick in a live run), and /readyz's
    cached verdict names the objective."""
    hm = health.get_monitor()
    added = install_model_slos(hm)
    assert added == 3
    assert install_model_slos(hm) == 0      # idempotent
    hm.tick()
    assert hm.probe("/readyz")[0] == 200
    model_health.get_model_monitor().note_wire_nonfinite("fc", 2)
    hm.tick()
    code, doc = hm.probe("/readyz")
    assert code == 503
    assert any("model_nonfinite" in r for r in doc["reasons"])
    assert doc["slos"]["model_nonfinite"]["firing"]
    # the verdict objective fires too (gauge 2 == diverged)
    assert doc["slos"]["model_divergence"]["firing"]
    reg = telemetry.get_registry()
    assert reg.counter_total("veles_slo_alert_firing",
                             objective="model_nonfinite") == 1.0


def test_register_health_check_names_divergence():
    hm = health.get_monitor()
    mon = model_health.get_model_monitor()
    mon.register_health(hm)
    hm.tick()
    assert hm.probe("/readyz")[0] == 200
    mon.note_wire_nonfinite("fc", 1)
    hm.tick()
    code, doc = hm.probe("/readyz")
    assert code == 503
    assert any("model diverged" in r for r in doc["reasons"])


# -- in-graph stats on a real compiled run -----------------------------


def test_xla_run_publishes_layer_stats_and_off_switch():
    """The compiled MNIST run exports per-GD-unit stat vectors as one
    fused extra output; the monitor sees finite norms for every layer
    and the judged loss. Flipping collection off removes them."""
    wf = make_wf("MHStatsOn", backend="xla", max_epochs=2)
    wf.run()
    doc = model_health.get_model_monitor().snapshot()
    assert doc["loss"] is not None and doc["verdict"] == "healthy"
    layer_names = set(doc["layers"])
    assert len(layer_names) == 2            # one per GD unit
    for stats in doc["layers"].values():
        assert stats["grad_norm"] > 0.0
        assert stats["weight_norm"] > 0.0
        assert 0.0 <= stats["update_ratio"] < 1.0
        assert stats["nonfinite"] == 0.0
    reg = telemetry.get_registry()
    assert reg.counter_total("veles_model_nonfinite_total") == 0.0

    with model_health.scoped() as fresh:
        wf2 = make_wf("MHStatsOff", backend="xla", max_epochs=2)
        wf2.xla_step.set_stats_enabled(False)
        wf2.run()
        assert fresh.snapshot()["layers"] == {}
        # the loss feed rides the decision, not the stat outputs
        assert fresh.snapshot()["loss"] is not None


def test_stats_stride_sentinels_are_skipped():
    """A stride longer than the run still publishes the t=0 sample
    and NEVER a sentinel row (negative norms must not reach the
    monitor)."""
    with model_health.scoped() as fresh:
        wf = make_wf("MHStride", backend="xla", max_epochs=1)
        wf.xla_step.stats_interval = 10 ** 6
        wf.xla_step.compiler.stats_stride = 10 ** 6
        wf.run()
        layers = fresh.snapshot()["layers"]
        assert layers, "the t=0 sample must publish"
        for stats in layers.values():
            assert stats["weight_norm"] >= 0.0


# -- verified checkpoints ----------------------------------------------


def test_manifest_verdict_stamped_and_auto_resume_skips(tmp_path):
    """Every checkpoint MANIFEST carries the verdict; resolve_auto
    skips 'diverged' blobs (counted), scan_checkpoints/`velescli
    checkpoints` surface the verdict column."""
    wf = make_wf("MHSnap", snapdir=str(tmp_path))
    wf.run()
    infos = scan_checkpoints(str(tmp_path))
    assert infos and all(i.health_verdict == "healthy"
                         for i in infos if i.status == "valid")
    healthy_names = {i.name for i in infos}
    # now the run diverges and a rolling checkpoint gets written
    model_health.get_model_monitor().note_wire_nonfinite("gd", 5)
    wf.snapshotter.export_snapshot(slot="current")
    bad = [i for i in scan_checkpoints(str(tmp_path))
           if i.name not in healthy_names]
    assert len(bad) == 1 and bad[0].health_verdict == "diverged"
    resolved = resolve_auto(str(tmp_path), prefixes={wf.snapshotter.prefix})
    assert resolved is not None
    _, name, _ = resolved
    assert name in healthy_names, \
        "auto-resume must fall back past the diverged blob"
    reg = telemetry.get_registry()
    assert reg.counter_total(
        "veles_checkpoint_diverged_skips_total") >= 1.0
    # the audit CLI shows the verdict column
    from veles.__main__ import checkpoints_main
    import io
    import contextlib
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = checkpoints_main([str(tmp_path), "--json"])
    assert rc == 0
    rows = json.loads(buf.getvalue())
    assert {r["verdict"] for r in rows} == {"healthy", "diverged"}


def test_serving_refresh_refuses_diverged_checkpoint(tmp_path):
    """The registry's checkpoint refresh path: a blob whose MANIFEST
    says diverged raises (reload() then degrades to the loaded
    version) instead of grafting blown-up weights onto a server."""
    from veles.serving.model import ArchiveModel
    store = FileSnapshotStore(str(tmp_path))
    tree = {"params": {"fc": {
        "weights": numpy.ones((2, 2), numpy.float32)}}}
    write_checkpoint(store, "bad_=1.ckpt.npz.gz", tree,
                     extra_meta={"model_health":
                                 {"verdict": "diverged",
                                  "reasons": ["nonfinite_wire:fc"]}})
    write_checkpoint(store, "good_=1.ckpt.npz.gz", tree)
    model = ArchiveModel.__new__(ArchiveModel)
    model.params = {"fc": {
        "weights": numpy.zeros((2, 2), numpy.float32)}}
    with pytest.raises(ValueError, match="diverged"):
        model.load_checkpoint(str(tmp_path / "bad_=1.ckpt.npz.gz"))
    assert model.load_checkpoint(
        str(tmp_path / "good_=1.ckpt.npz.gz")) == 1
    assert model.params["fc"]["weights"][0, 0] == 1.0


# -- rollback actuators ------------------------------------------------


def _pump_one_update(server, sreg, slave_wf, sid, lease,
                     poison=False):
    """Pull jobs until a TRAIN one, run it on the slave workflow, and
    push the (optionally poisoned) update; -> the handle reply."""
    loader_name = server.workflow.loader.name
    for _ in range(64):
        resp = server.handle(("job", sid, lease))
        assert resp[0] == "job", resp
        _, payload, job_id, epoch = resp[:4]
        if payload[loader_name][0] == CLASS_TRAIN:
            break
    else:
        pytest.fail("no train job served")
    sreg.apply_job(payload)
    run_iteration(slave_wf)
    update = sreg.generate_update()
    if poison:
        uname, entry = poison_update(update)
        assert entry.startswith("d")
    return server.handle(
        ("update", sid, lease, job_id, epoch, update))


def test_weight_guard_restores_pre_spike_weights():
    """Chaos satellite: a NaN-poisoned delta merges, the master-side
    counter increments, and the guard's same-handle tick restores the
    stash — canonical weights return to the pre-spike values
    exactly."""
    master_wf = make_wf("MHGuardMaster", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2,
                          rollback_on_divergence=True)
    _, sid, lease = server.handle(("hello", "guard-slave"))
    slave_wf = make_wf("MHGuardSlave")
    slave_wf.is_slave = True
    sreg = DistributionRegistry(slave_wf)

    assert _pump_one_update(server, sreg, slave_wf, sid,
                            lease) == ("ok",)
    w_stash = numpy.array(
        master_wf.forwards[0].weights.map_read().mem)
    assert _pump_one_update(server, sreg, slave_wf, sid, lease,
                            poison=True) == ("ok",)
    # the guard ticked inside handle(): weights are the stash again
    w_after = master_wf.forwards[0].weights.map_read().mem
    assert numpy.isfinite(w_after).all()
    numpy.testing.assert_array_equal(w_after, w_stash)
    assert server._weight_guard.rollback_count == 1
    reg = telemetry.get_registry()
    assert reg.counter_total("veles_model_nonfinite_total") >= 1.0
    verdict, _ = model_health.get_model_monitor().verdict_state()
    assert verdict == "suspect"             # latched until clean obs
    events = [e for e in telemetry.tracer.recent_events(50)
              if e.get("event") == "model_rollback"]
    assert events and events[-1]["source"] == "weight_guard"


def test_restore_stash_copies_instead_of_aliasing():
    """Review fix: Array.mem assignment aliases same-dtype arrays, so
    a restore must COPY — otherwise post-restore in-place merges
    corrupt the stash and a SECOND divergence restores post-spike
    values."""
    wf = make_wf("MHAlias", max_epochs=None)
    wf.decision.max_epochs = 2
    stash = wf.stash_state()
    fwd = wf.forwards[0]
    w0 = numpy.array(stash[fwd.name][0]["weights"])
    wf.restore_stash(stash)
    fwd.weights.map_write().mem[...] += 5.0     # the next merges
    numpy.testing.assert_array_equal(
        stash[fwd.name][0]["weights"], w0)      # stash untouched
    wf.restore_stash(stash)                     # second divergence
    numpy.testing.assert_array_equal(
        fwd.weights.map_read().mem, w0)


def test_nn_rollback_divergence_tick_restores():
    """Standalone actuator: NNRollback watches the verdict every
    cycle when rollback_on_divergence is set and restores its stash
    (cutting lr) without waiting for an epoch-loss blow-up."""
    prng.seed_all(31337)
    from veles.znicz_tpu.models import mnist
    saved = {k: getattr(root.mnist.loader, k, None)
             for k in ("minibatch_size", "n_train", "n_valid")}
    root.mnist.loader.update({"minibatch_size": 20,
                              "n_train": 100, "n_valid": 40})
    root.mnist.decision.max_epochs = 2
    try:
        wf = mnist.create_workflow(name="MHRollback")
        rb = wf.link_rollback(lr_cut=0.5)
        rb.rollback_on_divergence = True
        wf.initialize(device="numpy")
        wf.run()                            # 2 sane epochs -> stash
    finally:
        root.mnist.loader.update(
            {k: v for k, v in saved.items() if v is not None})
    assert rb._stash is not None and rb.rollback_count == 0
    stash_w = rb._stash[wf.forwards[0].name][0]["weights"]
    # poison the live weights + flip the verdict, then tick
    wf.forwards[0].weights.map_write().mem[0, 0] = numpy.nan
    model_health.get_model_monitor().note_wire_nonfinite("gd", 1)
    rb.run()
    assert rb.rollback_count == 1
    w = wf.forwards[0].weights.map_read().mem
    assert numpy.isfinite(w).all()
    numpy.testing.assert_array_equal(w, stash_w)
    assert wf.gds[0].lr_scale == pytest.approx(0.5)
    verdict, _ = model_health.get_model_monitor().verdict_state()
    assert verdict == "suspect"


# -- master-side surfaces (deterministic, handle-level) ----------------


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


def test_master_divergence_surfaces(tmp_path):
    """The diverged state, frozen (no rollback guard): SLO fires
    within a tick, /readyz names it, /debug/model + the velescli top
    row report the diverged verdict, and the master's next persisted
    checkpoint is stamped diverged and skipped by resolve_auto."""
    from veles.web_status import WebStatus
    store = FileSnapshotStore(str(tmp_path))
    master_wf = make_wf("MHSurf", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2,
                          checkpoint_store=store)
    hm = health.get_monitor()
    install_model_slos(hm)
    web = WebStatus(port=0)
    try:
        _, sid, lease = server.handle(("hello", "surf-slave"))
        slave_wf = make_wf("MHSurfSlave")
        slave_wf.is_slave = True
        sreg = DistributionRegistry(slave_wf)
        assert _pump_one_update(server, sreg, slave_wf, sid,
                                lease) == ("ok",)
        healthy_uri = server.persist_state("pre-spike")
        assert healthy_uri
        assert _pump_one_update(server, sreg, slave_wf, sid, lease,
                                poison=True) == ("ok",)
        verdict, _ = model_health.get_model_monitor().verdict_state()
        assert verdict == "diverged"
        # the SLO fires within ONE evaluation tick of the engine
        hm.tick()
        code, doc = hm.probe("/readyz")
        assert code == 503
        assert any("model_nonfinite" in r for r in doc["reasons"])
        # /debug/model over real HTTP
        base = "http://127.0.0.1:%d" % web.port
        mdoc = _get_json(base + "/debug/model")
        assert mdoc["verdict"] == "diverged"
        assert mdoc["nonfinite_total"] >= 1
        # the velescli top row (fleet scraper + renderer)
        from veles.fleet import fleet_snapshot, render_snapshot
        snap = fleet_snapshot([base], timeout=10.0)
        row = snap["targets"][0]
        assert row["model"]["verdict"] == "diverged"
        rendered = render_snapshot(snap)
        assert "verdict diverged" in rendered
        # the next master checkpoint carries the diverged stamp and
        # auto-resume falls back to the pre-spike one
        diverged_uri = server.persist_state("post-spike")
        assert diverged_uri
        infos = {i.name: i for i in scan_checkpoints(str(tmp_path))}
        assert len(infos) == 2
        verdicts = sorted(i.health_verdict for i in infos.values())
        assert verdicts == ["diverged", "healthy"]
        resolved = resolve_auto(str(tmp_path),
                                prefixes={master_wf.name})
        assert resolved is not None
        assert healthy_uri.endswith(resolved[1])
    finally:
        web.close()


def test_top_degrades_against_pre_issue15_target():
    """`velescli top` satellite: a live target that predates
    /debug/model scrapes into a normal row — no model key, no error,
    and the renderer stays silent about it."""
    import http.server
    import socketserver

    class OldHandler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.startswith("/healthz"):
                body, code = b'{"status": "ok"}', 200
            else:
                body, code = b"not found", 404
            self.send_response(code)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = socketserver.TCPServer(("127.0.0.1", 0), OldHandler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        from veles.fleet import render_snapshot, scrape_target
        row = scrape_target(
            "http://127.0.0.1:%d" % httpd.server_address[1],
            timeout=5.0)
        assert row["reachable"] and "error" not in row
        assert "model" not in row
        snap = {"ts": 0.0, "targets": [row],
                "fleet": {"targets": 1, "reachable": 1, "ready": 0,
                          "slaves": 0, "firing_slos": [],
                          "degraded": []}}
        assert "model:" not in render_snapshot(snap)
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_absorbed_slave_summary_rides_telemetry_path():
    """The __telemetry__ side channel: a pushed model summary lands
    slave-labelled on the master and folds into its detector."""
    master_wf = make_wf("MHAbsorb", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2)
    server._absorb_telemetry(
        {"model": {"loss": 0.7, "verdict": "healthy",
                   "layers": {"fc": {"grad_norm": 2.0,
                                     "weight_norm": 3.0,
                                     "update_ratio": 0.02,
                                     "nonfinite": 0}}}}, 11)
    doc = model_health.get_model_monitor().snapshot()
    assert "11" in doc["slaves"]
    reg = telemetry.get_registry()
    fam = reg.gauge("veles_model_loss")
    values = {items: c.value for items, c in fam.children()}
    assert values[(("slave", "11"),)] == 0.7


def test_serving_frontend_serves_debug_model():
    """The serving frontend answers /debug/model inline (same doc as
    web-status): drift gauges recorded by the batcher show up under
    'serving'."""
    from veles.serving.frontend import ServingFrontend
    from veles.serving.registry import ModelRegistry
    registry = ModelRegistry()
    front = ServingFrontend(registry, port=0)
    try:
        model_health.get_model_monitor().observe_serving(
            "toy", numpy.array([[0.9, 0.05, 0.05]]))
        doc = _get_json(
            "http://127.0.0.1:%d/debug/model" % front.port)
        assert doc["verdict"] == "healthy"
        assert "toy" in doc["serving"]
    finally:
        front.close()
        registry.close()


# -- chaos helper ------------------------------------------------------


def test_drop_slave_evicts_absorbed_model_summary():
    """Review fix: a departed slave's absorbed summary and its
    slave-labelled gauge children must not read as current forever."""
    master_wf = make_wf("MHEvict", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2)
    _, sid, _lease = server.handle(("hello", "evict-slave"))
    server._absorb_telemetry(
        {"model": {"loss": 0.9, "verdict": "healthy",
                   "layers": {"fc": {"grad_norm": 1.0,
                                     "weight_norm": 2.0,
                                     "update_ratio": 0.1,
                                     "nonfinite": 0}}}}, sid)
    mon = model_health.get_model_monitor()
    assert str(sid) in mon.snapshot()["slaves"]
    server.drop_slave(sid)
    assert str(sid) not in mon.snapshot()["slaves"]
    reg = telemetry.get_registry()
    for fam_name in ("veles_model_loss", "veles_model_grad_norm"):
        fam = reg.gauge(fam_name)
        assert not any(("slave", str(sid)) in items
                       for items, _ in fam.children()), fam_name


def test_poison_update_writes_through_noncontiguous():
    """Review fix: a strided/transposed delta view must be poisoned
    IN PLACE, not in a reshape copy that reads as success."""
    base = numpy.ones((4, 4), numpy.float32)
    view = base.T[::2]                      # non-contiguous
    assert not view.flags["C_CONTIGUOUS"]
    update = {"gd": {"dweights": view}}
    poison_update(update)
    assert not numpy.isfinite(view).all()


def test_poison_update_helper_contract():
    wf = make_wf("MHPoison", max_epochs=None)
    wf.decision.max_epochs = 2
    wf.is_slave = True
    sreg = DistributionRegistry(wf)
    wf.loader.run()
    run_iteration(wf)
    update = sreg.generate_update()
    uname, entry = poison_update(update)
    arr = update[uname][entry]
    assert not numpy.isfinite(arr.reshape(-1)[0])
    with pytest.raises(ValueError):
        poison_update({"unit": {"note": "no arrays here"}})


# -- the E2E acceptance ------------------------------------------------


def test_e2e_divergence_rollback_two_slaves():
    """ISSUE 15 acceptance: real master + 2 slaves over sockets,
    --rollback-on-divergence armed. One planned NaN-poisoned update:
    the divergence SLO fires within 2 evaluation ticks, /readyz flips
    naming the objective, exactly one rollback restores the pre-spike
    weights, and training runs on to match the unpoisoned sequential
    reference within the existing chaos tolerance."""
    w_ref = sequential_reference(max_epochs=2)

    master_wf = make_wf("MHE2EMaster", max_epochs=None)
    master_wf.loader.shuffle_enabled = False
    master_wf.loader._start_epoch(first=True)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2,
                          slave_timeout=5.0,
                          rollback_on_divergence=True)
    hm = health.get_monitor()
    install_model_slos(hm)
    server.start_background()

    slaves = [make_wf("MHE2ESlave%d" % i) for i in range(2)]
    for wf in slaves:
        wf.is_slave = True
    clients, errors = [], []
    poisoned = threading.Event()

    def run_slave(wf, idx):
        client = SlaveClient(
            wf, "127.0.0.1:%d" % server.bound_address[1],
            name="mh-%d" % idx, io_timeout=2.0, retry_base=0.02,
            retry_max=0.25, max_retries=25)
        clients.append(client)
        if idx == 1:
            orig = client.registry.generate_update
            state = {"n": 0}

            def poisoned_update():
                update = orig()
                state["n"] += 1
                # poison exactly ONE update, once a clean merge has
                # armed the guard's stash
                if state["n"] == 3 and not poisoned.is_set():
                    try:
                        poison_update(update)
                        poisoned.set()
                    except ValueError:
                        pass            # eval-only payload: next one
                return update

            client.registry.generate_update = poisoned_update
        try:
            client.run_forever()
        except ConnectionError:
            if not server.done.is_set():
                errors.append("gave up before done")

    threads = [threading.Thread(target=run_slave, args=(wf, i))
               for i, wf in enumerate(slaves)]
    for t in threads:
        t.start()

    # the moment the poisoned update merges, the verdict flips; two
    # engine ticks bound the alert latency
    deadline = time.monotonic() + 120
    fired = False
    while time.monotonic() < deadline:
        if poisoned.is_set() and \
                server._weight_guard.rollback_count >= 1:
            hm.tick()
            code, doc = hm.probe("/readyz")
            if any("model_nonfinite" in r
                   for r in doc.get("reasons", ())):
                assert code == 503
                fired = True
                break
            hm.tick()                   # tick #2 of the bound
            code, doc = hm.probe("/readyz")
            assert code == 503, doc
            assert any("model_nonfinite" in r
                       for r in doc["reasons"])
            fired = True
            break
        time.sleep(0.01)
    for t in threads:
        t.join(timeout=300)
    assert not errors, errors
    assert server.done.is_set(), server.status()
    assert poisoned.is_set()
    assert fired, "divergence SLO never fired"
    assert server._weight_guard.rollback_count == 1

    # the restored run converged onto the unpoisoned reference: the
    # only deviation is the single discarded minibatch delta
    w_master = numpy.asarray(
        master_wf.forwards[0].weights.map_read().mem)
    assert numpy.isfinite(w_master).all()
    numpy.testing.assert_allclose(w_master, w_ref, atol=0.02)

    doc = model_health.get_model_monitor().snapshot()
    assert doc["rollbacks"] == 1
    assert doc["nonfinite_total"] >= 1
    events = [e for e in telemetry.tracer.recent_events(100)
              if e.get("event") == "model_divergence"]
    assert any(e.get("verdict") == "diverged" for e in events)


# -- bench row ---------------------------------------------------------


@pytest.mark.slow
def test_model_stats_overhead_row_under_acceptance():
    """The bench acceptance (<2%) on this container — slow-marked:
    the off-on-off loop compiles three program variants."""
    import bench
    pct = bench.model_stats_overhead_pct(measure_chunks=2)
    assert 0.0 <= pct < 2.0, pct
