"""Gradient wire codec layer (ISSUE 7): round-trips over dtypes/
shapes/edge values, the pinned NaN/inf policy, error-feedback
convergence, the pickle-5 out-of-band frame format, hello codec
negotiation with counted fallback, and the acceptance wire-bytes
ratio (int8 <= 30% of uncompressed)."""

import pickle
import socket
import struct
import threading

import numpy
import pytest

from veles import compression, telemetry
from veles.client import SlaveClient
from veles.server import (MAX_FRAME_BYTES, MasterServer,
                          _frame_parts, decode_frame_payload,
                          recv_frame, send_frame)
from tests.test_service import make_wf

RNG = numpy.random.default_rng(1234)


# -- codec round-trips -------------------------------------------------


@pytest.mark.parametrize("codec", ["bf16", "int8"])
@pytest.mark.parametrize("shape", [(), (1,), (7,), (3, 4), (2, 3, 5)])
def test_roundtrip_shapes(codec, shape):
    c = compression.get_codec(codec)
    a = RNG.standard_normal(shape).astype(numpy.float32)
    for encode in (c.encode_update, c.encode_broadcast):
        c.reset()
        out = compression.decode(encode("k", a))
        assert out.shape == a.shape
        assert out.dtype == numpy.float32
        if codec == "bf16":
            # one bf16 round-trip keeps 8 mantissa bits
            assert numpy.abs(out - a).max() <= \
                numpy.abs(a).max() * 2.0 ** -8 + 1e-12
        else:
            spread = float(a.max() - a.min()) if a.size else 0.0
            assert numpy.abs(out - a).max() <= spread / 255.0 + 1e-12


def test_none_codec_is_passthrough_and_unknown_raises():
    assert compression.get_codec("none") is None
    raw = numpy.arange(4, dtype=numpy.float32)
    assert compression.decode(raw) is raw   # no tag -> untouched
    with pytest.raises(KeyError, match="unknown grad codec"):
        compression.get_codec("zstd")
    with pytest.raises(ValueError, match="unknown grad codec"):
        compression.decode({compression.TAG: "zstd"})


def test_int8_constant_and_zero_tensors_are_exact():
    c = compression.get_codec("int8")
    for value in (0.0, -3.25, 7.5):
        a = numpy.full((5, 5), value, numpy.float32)
        out = compression.decode(c.encode_broadcast("k", a))
        numpy.testing.assert_array_equal(out, a)


def test_int8_worst_case_scale_error_bound():
    """The documented bound holds even at the worst float32 spread:
    max abs error <= (hi - lo) / 255 (scale arithmetic runs in
    float64, so the spread cannot overflow to an inf scale)."""
    c = compression.get_codec("int8")
    a = numpy.array([-3e38, -1.0, 0.0, 2.5, 3e38], numpy.float32)
    payload = c.encode_broadcast("k", a)
    assert numpy.isfinite(payload["scale"])
    out = compression.decode(payload)
    assert numpy.isfinite(out).all()
    assert numpy.abs(out - a).max() <= (6e38 / 255.0) * 1.0001


def test_nonfinite_policy_pinned():
    """The documented policy, pinned: update deltas ZERO non-finite
    entries under every lossy codec (and keep them out of the
    residual); bf16 broadcasts preserve inf and canonicalize NaN;
    int8 broadcasts sanitize (an inf would destroy the scale)."""
    bad = numpy.array([numpy.nan, numpy.inf, -numpy.inf, 1.5, -2.0],
                      numpy.float32)
    for name in ("bf16", "int8", "topk"):
        c = compression.get_codec(name, topk_percent=100.0)
        out = compression.decode(c.encode_update("k", bad))
        assert numpy.isfinite(out).all(), name
        assert abs(out[3] - 1.5) < 0.01 and abs(out[4] + 2.0) < 0.02
        if c._residual:
            assert numpy.isfinite(c._residual["k"]).all(), name
    # bf16 broadcast: inf survives, NaN stays NaN (canonical quiet
    # NaN — a naive mantissa rounding would read back as inf)
    out = compression.decode(
        compression.get_codec("bf16").encode_broadcast("k", bad))
    assert numpy.isnan(out[0])
    assert out[1] == numpy.inf and out[2] == -numpy.inf
    # bf16 rounds past-max-finite values UP to inf (RNE semantics)
    big = numpy.array([numpy.finfo(numpy.float32).max], numpy.float32)
    assert compression.decode(
        compression.get_codec("bf16").encode_broadcast(
            "k", big))[0] == numpy.inf
    # int8 broadcast sanitizes
    out = compression.decode(
        compression.get_codec("int8").encode_broadcast("k", bad))
    assert numpy.isfinite(out).all()


def test_topk_ships_k_entries_and_residual_catches_up():
    c = compression.get_codec("topk", topk_percent=10.0)
    a = numpy.zeros(100, numpy.float32)
    a[:20] = numpy.arange(20, 0, -1, dtype=numpy.float32)
    payload = c.encode_update("k", a)
    assert payload["idx"].size == 10
    out = compression.decode(payload)
    # the largest-magnitude 10 shipped, exactly
    numpy.testing.assert_array_equal(numpy.sort(out[out != 0]),
                                     numpy.arange(11, 21,
                                                  dtype=numpy.float32))
    # the suppressed entries live in the residual and ship NEXT sync
    out2 = compression.decode(
        c.encode_update("k", numpy.zeros(100, numpy.float32)))
    numpy.testing.assert_array_equal(
        numpy.sort(out2[out2 != 0]),
        numpy.arange(1, 11, dtype=numpy.float32))


def test_topk_percent_100_is_dense_exact():
    c = compression.get_codec("topk", topk_percent=100.0)
    a = RNG.standard_normal((8, 8)).astype(numpy.float32)
    numpy.testing.assert_array_equal(
        compression.decode(c.encode_update("k", a)), a)


@pytest.mark.parametrize("codec,percent", [("int8", 1.0),
                                           ("topk", 10.0)])
def test_error_feedback_converges_to_uncompressed(codec, percent):
    """The regression the residuals exist for: the decoded sum of N
    compressed syncs equals the raw delta sum MINUS exactly the
    residual still held (acc + residual == total, an identity), and
    the tracking error does NOT grow with N — without feedback int8
    would random-walk at ~sqrt(N) quantization errors."""
    c = compression.get_codec(codec, topk_percent=percent)
    rng = numpy.random.default_rng(7)
    total = numpy.zeros(200, numpy.float32)
    acc = numpy.zeros_like(total)
    errs = []
    for i in range(120):
        d = (rng.standard_normal(200) * 0.01).astype(numpy.float32)
        total += d
        acc += compression.decode(c.encode_update("w", d))
        errs.append(float(numpy.abs(acc - total).max()))
    residual = c._residual["w"]
    numpy.testing.assert_allclose(acc + residual, total, atol=1e-4)
    # bounded, not growing: the late-run error is no worse than a
    # small multiple of the early-run error
    assert max(errs[60:]) <= max(errs[:20]) * 3.0 + 1e-3
    assert errs[-1] < 0.1


def test_codec_telemetry_counts_shrink():
    c = compression.get_codec("int8")
    a = RNG.standard_normal(1000).astype(numpy.float32)
    c.encode_update("k", a)
    compression.decode(c.encode_broadcast("k", a))
    reg = telemetry.get_registry()
    raw = reg.counter_total("veles_grad_codec_raw_bytes_total",
                            codec="int8")
    enc = reg.counter_total("veles_grad_codec_encoded_bytes_total",
                            codec="int8")
    assert raw == 2 * a.nbytes
    assert 0 < enc <= raw / 3.9     # 4x shrink, both directions


# -- GD-unit threading (the nn_units hook points) ----------------------


def run_iteration(wf):
    from veles.loader.base import CLASS_TRAIN
    for u in wf.forwards:
        u.run()
    wf.evaluator.run()
    if wf.loader.minibatch_class == CLASS_TRAIN:
        for gd in reversed(wf.gds):
            gd.run()


def _sync_rounds(codec, rounds=12):
    """Drive master/slave registries in process for a few jobs with
    ``codec`` on both directions; -> final master weights."""
    from veles.distributable import DistributionRegistry
    master = make_wf("CodecM-%s" % codec, max_epochs=None)
    master.decision.max_epochs = 2
    slave = make_wf("CodecS-%s" % codec)
    slave.is_slave = True
    enc = compression.get_codec(codec, topk_percent=25.0)
    if enc is not None:
        master.grad_codec_by_slave = {
            1: compression.get_codec(codec, topk_percent=25.0)}
        slave.grad_codec = enc
    mreg = DistributionRegistry(master)
    sreg = DistributionRegistry(slave)
    for _ in range(rounds):
        job = mreg.generate_job(1)
        if job.get(master.loader.name) is None:
            break
        sreg.apply_job(job)
        run_iteration(slave)
        mreg.apply_update(sreg.generate_update(), 1)
    return numpy.array(master.forwards[0].weights.map_read().mem)


@pytest.mark.parametrize("codec", ["bf16", "int8", "topk"])
def test_gd_unit_sync_matches_uncompressed(codec):
    """Satellite: repeated compressed syncs through the REAL GD-unit
    hook points land within tolerance of the uncompressed result —
    the error-feedback residuals work where they are actually
    wired."""
    w_ref = _sync_rounds("none")
    w = _sync_rounds(codec)
    assert numpy.isfinite(w).all()
    numpy.testing.assert_allclose(w, w_ref, atol=5e-3)


# -- wire framing (pickle protocol 5, out-of-band buffers) -------------


def _pipe():
    return socket.socketpair()


def test_frame_out_of_band_roundtrip():
    """ndarray payloads ship as out-of-band buffers (no monolithic
    blob copy) and reconstruct equal AND writable on the far side."""
    obj = ("update", 1, "lease", 5, 0,
           {"gd": {"dweights": RNG.standard_normal(
               (64, 32)).astype(numpy.float32)}})
    assert len(_frame_parts(obj)) > 1     # buffers really split out
    a, b = _pipe()
    t = threading.Thread(target=send_frame, args=(a, obj))
    t.start()
    got = recv_frame(b)
    t.join()
    numpy.testing.assert_array_equal(
        got[5]["gd"]["dweights"], obj[5]["gd"]["dweights"])
    assert got[5]["gd"]["dweights"].flags.writeable
    assert got[:5] == obj[:5]
    a.close(), b.close()


def test_frame_bufferless_stays_bare_pickle():
    """Control frames (pings, acks) keep the single-part bare-pickle
    payload — byte-compatible with a pre-codec recv."""
    parts = _frame_parts(("ping", 1, "lease"))
    assert len(parts) == 1 and parts[0][:1] == b"\x80"
    assert decode_frame_payload(parts[0]) == ("ping", 1, "lease")


def test_decode_frame_payload_accepts_legacy_pickle():
    obj = ("job", {"x": [1, 2, 3]}, 7, 0)
    assert decode_frame_payload(
        pickle.dumps(obj, protocol=4)) == obj


def test_frame_hmac_tamper_rejected():
    obj = ("update", 1, "l", 2, 0,
           {"gd": {"dweights": numpy.ones(100, numpy.float32)}})
    import hashlib
    import hmac as hmac_mod
    from veles.server import _FRAME_OVERHEAD, _secret
    parts = _frame_parts(obj)
    blob = bytearray(b"".join(bytes(p) for p in parts))
    tag = hmac_mod.new(_secret(), blob, hashlib.sha256).digest()
    blob[len(blob) // 2] ^= 0xFF          # bit rot mid-tensor
    frame = struct.pack(">I", len(blob)) + tag + bytes(blob)
    assert len(frame) == len(blob) + _FRAME_OVERHEAD
    a, b = _pipe()
    a.sendall(frame)
    with pytest.raises(ConnectionError, match="HMAC"):
        recv_frame(b)
    a.close(), b.close()


def test_frame_buffer_accounting_mismatch_rejected():
    good = b"".join(bytes(p) for p in _frame_parts(
        {"w": numpy.ones(16, numpy.float32)}))
    assert good[:1] == b"\xf5"
    with pytest.raises(ConnectionError, match="mismatch"):
        decode_frame_payload(good[:-8])   # truncated buffer tail
    with pytest.raises(ConnectionError):
        decode_frame_payload(b"\xf5\x00")  # garbled header


def test_graphics_framing_reuses_hardened_helpers():
    """Satellite: the graphics channel now rides the server's capped
    framing — an oversized length header is refused BEFORE any
    allocation, and a normal npz frame round-trips."""
    from veles import graphics
    a, b = _pipe()
    payload = graphics.pack_payload({"plot": "w"},
                                    {"y": numpy.arange(5.0)})
    graphics.send_frame(a, payload)
    blob = graphics.recv_frame(b)
    meta, arrays = graphics.unpack_payload(blob)
    assert meta == {"plot": "w"}
    numpy.testing.assert_array_equal(arrays["y"], numpy.arange(5.0))
    a.sendall(struct.pack(">I", MAX_FRAME_BYTES + 1))
    with pytest.raises(ConnectionError, match="cap"):
        graphics.recv_frame(b)
    a.close(), b.close()


# -- hello negotiation -------------------------------------------------


def test_hello_negotiation_master_config_wins():
    wf = make_wf("NegoM", max_epochs=None)
    wf.decision.max_epochs = 2
    server = MasterServer(wf, "127.0.0.1:0", max_epochs=2,
                          grad_codec="int8")
    # agreeing slave: codec granted, per-slave encoder minted
    resp = server.handle(("hello", "new-slave", "int8"))
    assert resp[0] == "welcome" and resp[3] == "int8"
    assert wf.grad_codec_by_slave[resp[1]].name == "int8"
    assert server.faults["codec_fallbacks"] == 0
    # pre-codec peer (2-tuple hello): falls back, counted, 3-tuple
    # welcome so welcome[:3] unpacking keeps working
    resp_old = server.handle(("hello", "old-slave"))
    assert resp_old[0] == "welcome" and len(resp_old) == 3
    assert server.faults["codec_fallbacks"] == 1
    assert resp_old[1] not in wf.grad_codec_by_slave
    # differently-configured slave: same counted fallback — but the
    # welcome stays a 4-tuple ("none"): its LENGTH tells a
    # codec-aware slave this master speaks the out-of-band frames
    resp_mis = server.handle(("hello", "mis-slave", "topk"))
    assert len(resp_mis) == 4 and resp_mis[3] == "none"
    assert server.faults["codec_fallbacks"] == 2
    # status surfaces the negotiated codec per slave
    st = server.status()
    assert st["grad_codec"] == "int8"
    assert st["slaves"][str(resp[1])]["codec"] == "int8"
    assert st["slaves"][str(resp_old[1])]["codec"] == "none"
    # dropping the lease drops the encoder (and its residual state)
    server.drop_slave(resp[1])
    assert resp[1] not in wf.grad_codec_by_slave


def test_hello_none_master_declines_offer():
    wf = make_wf("NegoNone", max_epochs=None)
    wf.decision.max_epochs = 2
    server = MasterServer(wf, "127.0.0.1:0", max_epochs=2)
    resp = server.handle(("hello", "eager", "bf16"))
    assert len(resp) == 4 and resp[3] == "none"
    assert server.faults["codec_fallbacks"] == 1
    resp2 = server.handle(("hello", "plain", "none"))
    assert len(resp2) == 4 and resp2[3] == "none"
    assert server.faults["codec_fallbacks"] == 1   # agreement, no count


def test_topk_percent_rides_welcome_master_wins():
    """Master config wins for the sparsity level too: a slave
    configured with a different --grad-topk-percent adopts the
    master's K from the welcome instead of silently shipping a
    different fraction of each delta."""
    wf = make_wf("NegoK", max_epochs=None)
    wf.decision.max_epochs = 2
    server = MasterServer(wf, "127.0.0.1:0", max_epochs=2,
                          grad_codec="topk", grad_topk_percent=5.0)
    resp = server.handle(("hello", "k-slave", "topk"))
    assert resp[3] == "topk" and resp[4] == 5.0
    server.start_background()
    swf = make_wf("NegoKS")
    swf.is_slave = True
    client = SlaveClient(swf, "127.0.0.1:%d" % server.bound_address[1],
                         name="k", grad_codec="topk",
                         grad_topk_percent=1.0, ping_interval=0)
    client.connect()
    assert swf.grad_codec.topk_percent == 5.0
    assert client._codec_active == ("topk", 5.0)
    client._close_sock()
    server.done.set()


def test_unknown_codec_rejected_at_construction():
    wf = make_wf("NegoBad", max_epochs=None)
    wf.decision.max_epochs = 2
    with pytest.raises(ValueError, match="unknown grad codec"):
        MasterServer(wf, "127.0.0.1:0", max_epochs=2,
                     grad_codec="zstd")
    swf = make_wf("NegoBadS")
    swf.is_slave = True
    with pytest.raises(ValueError, match="unknown grad codec"):
        SlaveClient(swf, "127.0.0.1:1", grad_codec="zstd")


def test_codec_mismatch_over_real_sockets_degrades_not_crashes():
    """Acceptance: a mismatched slave trains to completion
    UNCOMPRESSED — counted warning on both sides, never a crash."""
    m = make_wf("MisM", max_epochs=None)
    m.decision.max_epochs = 2
    server = MasterServer(m, "127.0.0.1:0", max_epochs=2,
                          grad_codec="int8")
    server.start_background()
    s = make_wf("MisS")
    s.is_slave = True
    client = SlaveClient(s, "127.0.0.1:%d" % server.bound_address[1],
                         name="mis", grad_codec="bf16")
    jobs = client.run_forever()
    assert jobs > 0 and server.done.is_set()
    assert client.codec_fallbacks >= 1
    assert client._codec_active[0] == "none"
    assert server.faults["codec_fallbacks"] >= 1
    assert telemetry.get_registry().counter_total(
        "veles_slave_codec_fallbacks_total") >= 1


# -- mixed-version frame compatibility ---------------------------------


def _old_recv_frame(sock):
    """What a pre-PR-7 peer does: pickle.loads over the whole
    authenticated payload — no out-of-band format knowledge."""
    from veles.server import _recv_exact
    header = _recv_exact(sock, 4)
    size, = struct.unpack(">I", header)
    _recv_exact(sock, 32)                 # tag (authenticity tested
    return pickle.loads(_recv_exact(sock, size))   # elsewhere)


def test_old_slave_gets_legacy_frames_from_new_master():
    """Rolling upgrade, master first: a pre-codec slave (2-tuple
    hello, monolithic-pickle recv) must be able to read EVERY reply —
    including the array-carrying job payload, which a new-format
    frame would crash with UnpicklingError."""
    wf = make_wf("LegacyM", max_epochs=None)
    wf.decision.max_epochs = 2
    server = MasterServer(wf, "127.0.0.1:0", max_epochs=2,
                          grad_codec="int8")
    server.start_background()
    sock = socket.create_connection(server.bound_address, timeout=10)
    # old peers pickle monolithically — send_frame(legacy=True) is
    # byte-shape-compatible with what they produced
    send_frame(sock, ("hello", "old-peer"), legacy=True)
    welcome = _old_recv_frame(sock)
    assert welcome[0] == "welcome" and len(welcome) == 3
    send_frame(sock, ("job", welcome[1], welcome[2]), legacy=True)
    resp = _old_recv_frame(sock)          # ships full ndarrays
    assert resp[0] == "job"
    payload = resp[1]
    arrays = [v for unit in payload.values() if isinstance(unit, dict)
              for v in unit.values()]
    assert any(isinstance(v, numpy.ndarray) for v in arrays)
    # and uncompressed: the int8-wanting master fell back for us
    assert not any(isinstance(v, dict) and compression.TAG in v
                   for unit in payload.values() if isinstance(unit, dict)
                   for v in unit.values())
    sock.close()
    server.done.set()


def test_new_slave_pins_legacy_frames_against_old_master():
    """Rolling upgrade, slaves first: an OLD master answers hello
    with a 3-tuple welcome in a monolithic frame — the new client
    must notice and pin its own sends to legacy frames it can read."""
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen()
    seen = {}

    def old_master():
        conn, _ = listener.accept()
        hello = _old_recv_frame(conn)
        seen["hello"] = hello
        send_frame(conn, ("welcome", 1, "lease-x"), legacy=True)
        # the client's next frame must be a LEGACY payload: read it
        # the old way, which crashes on the new format
        seen["next"] = _old_recv_frame(conn)
        conn.close()

    t = threading.Thread(target=old_master)
    t.start()
    wf = make_wf("LegacyS")
    wf.is_slave = True
    client = SlaveClient(
        wf, "127.0.0.1:%d" % listener.getsockname()[1],
        io_timeout=10.0, grad_codec="int8", ping_interval=0)
    client.connect()
    assert client._legacy_frames is True
    assert client._codec_active[0] == "none"
    # an update frame (array-carrying) round-trips through the old
    # master's monolithic recv without UnpicklingError
    try:
        client._roundtrip(("update", 1, "lease-x", 1, 0,
                           {"gd": {"dweights": numpy.ones(
                               8, numpy.float32)}}))
    except ConnectionError:
        pass                              # old_master hangs up after
    t.join(timeout=10)
    assert seen["hello"][2] == "int8"     # extra element was harmless
    assert seen["next"][0] == "update"
    numpy.testing.assert_array_equal(
        seen["next"][5]["gd"]["dweights"], numpy.ones(8, numpy.float32))
    listener.close()


# -- the acceptance byte ratio -----------------------------------------


def _wire_tx_bytes():
    """tx-side frame bytes, EXCLUDING slave-labelled absorbed copies
    (co-located master+slave share one registry, and the slave pushes
    its counter state to the master — counting those too would double
    every frame)."""
    state = telemetry.get_registry().counter_state(
        exclude_label_keys=("slave",))
    return sum(v for (name, items), v in state.items()
               if name == "veles_wire_bytes_total"
               and ("direction", "tx") in items)


def _measure_wire_bytes_per_job(codec):
    m = make_wf("WireM-%s" % codec, max_epochs=None)
    m.decision.max_epochs = 1
    server = MasterServer(m, "127.0.0.1:0", max_epochs=1,
                          grad_codec=codec)
    server.start_background()
    s = make_wf("WireS-%s" % codec)
    s.is_slave = True
    before = _wire_tx_bytes()
    jobs = SlaveClient(
        s, "127.0.0.1:%d" % server.bound_address[1],
        name="wire-%s" % codec, grad_codec=codec).run_forever()
    assert jobs > 0
    return (_wire_tx_bytes() - before) / jobs


def test_int8_wire_bytes_at_most_30_percent_of_none():
    """Acceptance: grad_sync bytes/step under int8 <= 30% of the
    'none' codec's, measured from the SAME veles_wire_bytes_total
    counters the runtime increments (4x on both directions leaves
    plenty of room for frame/telemetry overhead)."""
    none_bpj = _measure_wire_bytes_per_job("none")
    int8_bpj = _measure_wire_bytes_per_job("int8")
    assert none_bpj > 300_000     # full fp32 weights really shipped
    assert int8_bpj / none_bpj <= 0.30, (int8_bpj, none_bpj)
