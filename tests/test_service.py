"""Service layer: snapshot/resume, CLI, launcher, master↔slave wire
protocol (SURVEY.md §2.7, §3.3, §3.4, §4 "Distributed tests")."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy
import pytest

import veles.prng as prng
from veles.config import root

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_wf(name, backend="numpy", max_epochs=2, snapdir=None):
    prng.seed_all(555)
    from veles.znicz_tpu.models import mnist
    root.mnist.loader.minibatch_size = 50
    root.mnist.loader.n_train = 500
    root.mnist.loader.n_valid = 100
    root.mnist.decision.max_epochs = max_epochs
    cfg = dict(snapshotter_config={"directory": snapdir}) \
        if snapdir else {}
    from veles.znicz_tpu.standard_workflow import StandardWorkflow
    wf = StandardWorkflow(
        None, name=name,
        layers=root.mnist.layers,
        loader_factory=lambda w: mnist.MnistLoader(
            w, name="loader", minibatch_size=50),
        decision_config=root.mnist.decision.to_dict(),
        **cfg)
    wf.initialize(device=backend)
    return wf


def test_snapshot_resume(tmp_path):
    snapdir = str(tmp_path)
    wf = make_wf("SnapWf", snapdir=snapdir)
    wf.run()
    assert wf.snapshotter.destination, "no snapshot written"
    assert os.path.exists(wf.snapshotter.destination)

    # resume into a FRESH workflow; training continues from the saved
    # best state rather than from scratch
    from veles.snapshotter import load_snapshot
    state = load_snapshot(wf.snapshotter.destination)
    wf2 = make_wf("SnapWf2", max_epochs=3)
    wf2.restore_state(state)
    # the snapshot is of the BEST point (improved gate), not the end
    assert wf2.decision.epoch_number == wf.decision.best_epoch
    assert numpy.allclose(
        wf2.forwards[0].weights.map_read().mem,
        state["params"][wf.forwards[0].name]["weights"])
    wf2.run()
    assert wf2.decision.epoch_number == 3


def test_snapshot_resume_xla(tmp_path):
    wf = make_wf("SnapX", backend="cpu", snapdir=str(tmp_path))
    wf.run()
    from veles.snapshotter import load_snapshot
    state = load_snapshot(wf.snapshotter.destination)
    wf2 = make_wf("SnapX2", backend="cpu", max_epochs=3)
    wf2.restore_state(state)
    wf2.run()
    assert wf2.decision.epoch_number == 3
    err = wf2.decision.history[-1]["validation"]["metric"]
    assert err <= wf.decision.history[-1]["validation"]["metric"] + 0.05


class _BlobHandler:
    """Minimal in-process object server (PUT/GET/DELETE /name,
    GET / -> JSON list) for the HTTPSnapshotStore round-trip."""

    @staticmethod
    def serve():
        import http.server
        import json as _json
        import threading
        blobs = {}

        class H(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _name(self):
                return self.path.lstrip("/")

            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                blobs[self._name()] = self.rfile.read(n)
                self.send_response(201)
                self.end_headers()

            def do_GET(self):
                name = self._name()
                if not name or name.endswith("/"):
                    # prefix list (S3-style): GET <prefix>/ returns
                    # the full object paths under it
                    body = _json.dumps(
                        sorted(n for n in blobs
                               if n.startswith(name))).encode()
                elif name in blobs:
                    body = blobs[name]
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_DELETE(self):
                existed = blobs.pop(self._name(), None) is not None
                self.send_response(204 if existed else 404)
                self.end_headers()

        server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        return server, blobs


def test_snapshot_http_store_roundtrip():
    """Snapshot + resume through the REMOTE store (SURVEY §2.7
    alternate-backend row): snapshots land on an HTTP object server,
    retention DELETEs stale names there, and --snapshot-style resume
    loads straight from the http:// URI."""
    from veles.snapshotter import load_snapshot
    server, blobs = _BlobHandler.serve()
    try:
        url = "http://127.0.0.1:%d/ckpts" % server.server_address[1]
        prng.seed_all(555)
        from veles.znicz_tpu.models import mnist
        root.mnist.loader.minibatch_size = 50
        root.mnist.loader.n_train = 500
        root.mnist.loader.n_valid = 100
        root.mnist.decision.max_epochs = 2
        from veles.znicz_tpu.standard_workflow import StandardWorkflow
        wf = StandardWorkflow(
            None, name="SnapHTTP", layers=root.mnist.layers,
            loader_factory=lambda w: mnist.MnistLoader(
                w, name="loader", minibatch_size=50),
            decision_config=root.mnist.decision.to_dict(),
            snapshotter_config={"store": url})
        wf.initialize(device="numpy")
        wf.run()
        dest = wf.snapshotter.destination
        assert dest.startswith(url), dest
        # blobs really live on the server, within retention
        assert blobs and len(
            [n for n in blobs if n.startswith("ckpts/")]) \
            <= wf.snapshotter.keep
        # list() works against this very endpoint shape and filters/
        # normalizes like the file store (ADVICE r4): base-relative
        # .ckpt. names only
        listed = wf.snapshotter.store.list()
        assert listed == sorted(
            n[len("ckpts/"):] for n in blobs
            if n.startswith("ckpts/") and ".ckpt." in n)
        assert all("/" not in n and ".ckpt." in n for n in listed)
        state = load_snapshot(dest)
        wf2 = make_wf("SnapHTTP2", max_epochs=3)
        wf2.restore_state(state)
        assert wf2.decision.epoch_number == wf.decision.best_epoch
        wf2.run()
        assert wf2.decision.epoch_number == 3
    finally:
        server.shutdown()
        server.server_close()


def test_snapshot_store_failure_escalates(tmp_path):
    """Transient store failures warn and continue; a store that fails
    ``max_store_failures`` times IN A ROW raises — a permanently dead
    backend must not silently disable checkpointing for a whole run
    (ADVICE r4). A success in between resets the counter."""
    from veles.snapshotter import FileSnapshotStore

    wf = make_wf("SnapFail", max_epochs=1,
                 snapdir=str(tmp_path / "snaps"))
    wf.run()
    snap = wf.snapshotter

    class FlakyStore(FileSnapshotStore):
        broken = True

        def stream(self, name):
            if self.broken:
                raise OSError("store down")
            return super().stream(name)

    snap._store = FlakyStore(str(tmp_path / "flaky"))
    assert snap.max_store_failures == 3
    assert snap.export_snapshot() is None
    assert snap.export_snapshot() is None
    snap._store.broken = False           # success resets the counter
    assert snap.export_snapshot() is not None
    assert snap._store_failures == 0
    snap._store.broken = True
    assert snap.export_snapshot() is None
    assert snap.export_snapshot() is None
    with pytest.raises(OSError):
        snap.export_snapshot()


def test_cli_end_to_end(tmp_path):
    """Drive the real CLI: sample module + overrides + result file."""
    result = tmp_path / "result.json"
    graph = tmp_path / "graph.dot"
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=REPO)
    base = [sys.executable, "-m", "veles",
            os.path.join(REPO, "veles/znicz_tpu/models/mnist.py"),
            "--seed", "99", "-d", "cpu", "--no-stats",
            "root.mnist.decision.max_epochs=2",
            "root.mnist.loader.n_train=300",
            "root.mnist.loader.n_valid=100",
            "root.mnist.loader.minibatch_size=50"]
    out = subprocess.run(
        base + ["--result-file", str(result)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    data = json.loads(result.read_text())
    assert len(data["history"]) == 2
    assert data["best_metric"] < 0.9

    out = subprocess.run(
        base + ["--workflow-graph", str(graph)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "digraph" in graph.read_text()


def test_request_kind_counter_is_bounded():
    """Admission hardening (zlint unbounded-cardinality): the frame
    chooses the request kind string, but the per-kind counter cache
    and its Prometheus label universe must not be the wire's to grow
    — unknown kinds fold into one ``other`` bucket."""
    from veles.server import _REQUEST_KINDS, _resolve_request_kind
    for kind in _REQUEST_KINDS:
        assert _resolve_request_kind(kind) == kind
    assert _resolve_request_kind("jailbreak") == "other"
    assert _resolve_request_kind("job2") == "other"
    assert _resolve_request_kind(b"\xff" * 64) == "other"
    assert _resolve_request_kind(None) == "other"
    # the dispatched universe is exactly the bounded label set
    assert _REQUEST_KINDS == {"hello", "ping", "job", "update"}


def test_master_slave_protocol():
    """In-process master + 2 slaves over localhost TCP: job/update
    round-trips, weight averaging, slave-drop requeue (§3.3, §4)."""
    from veles.server import MasterServer
    from veles.client import SlaveClient

    master_wf = make_wf("MasterWf", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2)
    server.start_background()
    addr = "127.0.0.1:%d" % server.bound_address[1]

    w0 = numpy.array(master_wf.forwards[0].weights.map_read().mem)

    slaves = [make_wf("SlaveWf%d" % i) for i in range(2)]
    for s in slaves:
        s.is_slave = True
    counts = []

    def run_slave(wf):
        client = SlaveClient(wf, addr, name=wf.name)
        counts.append(client.run_forever())

    threads = [threading.Thread(target=run_slave, args=(s,))
               for s in slaves]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert server.done.is_set()
    assert sum(counts) >= 2 * (500 // 50 + 100 // 50)  # 2 epochs of jobs
    # master weights moved (averaged in from slave updates)
    w1 = master_wf.forwards[0].weights.map_read().mem
    assert not numpy.allclose(w0, w1)


def test_single_slave_matches_standalone():
    """Delta-shipping makes one-slave distributed training EXACTLY
    sequential SGD: master hands weights + a minibatch job, slave
    trains it, ships the delta, master applies it verbatim — the final
    weights equal a standalone run over the same minibatch order
    (shuffling disabled: the master deliberately shuffles with a
    separate PRNG stream, so bitwise parity needs a fixed order)."""
    from veles.server import MasterServer
    from veles.client import SlaveClient

    from veles.loader.base import CLASS_TRAIN

    def unshuffled(name, **kw):
        wf = make_wf(name, **kw)
        wf.loader.shuffle_enabled = False
        wf.loader._start_epoch(first=True)   # regenerate the order
        return wf

    # reference: plain sequential SGD over exactly 2 epochs of serves.
    # (wf.run() is NOT the reference here: its decision gates off the
    # final minibatch's GD update once `complete` fires — a stop-logic
    # artifact the master/slave protocol doesn't replicate.)
    ref = unshuffled("StandaloneRef", max_epochs=2)
    loader = ref.loader
    for _ in range(2 * ref.loader.effective_batches_per_epoch):
        loader.run()
        for u in ref.forwards:
            u.run()
        ref.evaluator.run()
        if loader.minibatch_class == CLASS_TRAIN:
            for gd in reversed(ref.gds):
                gd.run()
    w_ref = numpy.array(ref.forwards[0].weights.map_read().mem)

    master_wf = unshuffled("Master1", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2)
    server.start_background()
    addr = "127.0.0.1:%d" % server.bound_address[1]
    slave = unshuffled("Slave1")
    slave.is_slave = True
    SlaveClient(slave, addr, name="s1").run_forever()
    assert server.done.is_set()
    w_master = master_wf.forwards[0].weights.map_read().mem
    numpy.testing.assert_allclose(w_master, w_ref, atol=1e-6)


def test_xla_slave_trains():
    """A slave on the FUSED XLA backend: weights pushed by the master
    re-upload per job (refresh_device), train, sync back, ship deltas."""
    from veles.server import MasterServer

    from veles.launcher import Launcher
    from veles.znicz_tpu.standard_workflow import StandardWorkflow
    from veles.znicz_tpu.models import mnist

    master_wf = make_wf("MasterXla", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2)
    server.start_background()
    addr = "127.0.0.1:%d" % server.bound_address[1]
    w0 = numpy.array(master_wf.forwards[0].weights.map_read().mem)

    # the REAL slave surface: the Launcher flags is_slave before
    # initialize, which pins the per-step (non-scan) execution mode
    prng.seed_all(555)
    slave = StandardWorkflow(
        None, name="SlaveXla", layers=root.mnist.layers,
        loader_factory=lambda w: mnist.MnistLoader(
            w, name="loader", minibatch_size=50),
        decision_config={"max_epochs": 2})
    launcher = Launcher(device="cpu", master_address=addr, stats=False)
    launcher.initialize(slave)
    assert slave.xla_step is not None \
        and not slave.xla_step.scan_mode   # slaves stay per-step
    launcher.run()
    assert server.done.is_set()
    w1 = master_wf.forwards[0].weights.map_read().mem
    assert not numpy.allclose(w0, w1)
    assert numpy.isfinite(w1).all()


def test_wire_protocol_carries_all_params():
    """The master↔slave link must ship EVERY forward parameter —
    attention's weights_out / FFN's weights2 included, not just
    weights/bias."""
    from veles.znicz_tpu.ops.attention import MultiHeadAttention
    from tests.test_conv_stack import build

    prng.seed_all(77)
    wf, feed, fwd, gd, x, err, comp = build(
        MultiHeadAttention, input_shape=(2, 8, 8), gd_kwargs={},
        heads=2)
    payload = gd.generate_data_for_slave()
    assert set(payload) >= {"weights", "weights_out"}

    # slave side: apply master weights, "train" (mutate), ship deltas
    gd.apply_data_from_master(payload)
    fwd.weights_out.map_write()
    fwd.weights_out.mem[...] += 0.25
    update = gd.generate_data_for_master()
    assert "dweights_out" in update
    numpy.testing.assert_allclose(update["dweights_out"], 0.25,
                                  atol=1e-6)
    numpy.testing.assert_allclose(update["dweights"], 0.0, atol=1e-6)

    # master side: deltas apply verbatim
    before = numpy.array(fwd.weights_out.mem)
    gd.apply_data_from_slave(update)
    numpy.testing.assert_allclose(
        fwd.weights_out.mem, before + 0.25, atol=1e-6)


def test_drop_slave_requeues():
    from veles.loader.base import CLASS_TRAIN
    wf = make_wf("DropWf")
    loader = wf.loader
    loader.master_start_epoch()
    total = len(loader._pending_jobs)
    job = loader.generate_data_for_slave(slave=7)
    assert job is not None and len(loader._pending_jobs) == total - 1
    loader.drop_slave(7)
    assert len(loader._pending_jobs) == total
    assert loader._pending_jobs[0] == job


def test_cli_background_daemon(tmp_path):
    """--background detaches: the foreground process returns
    immediately with the daemon pid; the daemon finishes the run and
    writes the result file + log (SURVEY.md §2.7 CLI row)."""
    result = tmp_path / "result.json"
    log = tmp_path / "daemon.log"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, "-m", "veles",
         os.path.join(REPO, "veles/znicz_tpu/models/mnist.py"),
         "--seed", "99", "-d", "numpy", "--no-stats",
         "root.mnist.decision.max_epochs=1",
         "root.mnist.loader.n_train=120",
         "root.mnist.loader.n_valid=40",
         "root.mnist.loader.minibatch_size=40",
         "--result-file", str(result),
         "--background", "--log-file", str(log)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    pid = doc["daemon_pid"]
    assert pid > 0
    # the daemon runs on after the foreground returned: poll for its
    # result file
    deadline = time.time() + 180
    while time.time() < deadline and not result.exists():
        time.sleep(0.5)
    assert result.exists(), "daemon never wrote the result file"
    data = json.loads(result.read_text())
    assert len(data["history"]) == 1


def test_master_dashboard_shows_slaves():
    """The master's web dashboard reports cluster topology from the
    live server registry: joined slaves with job counts (§5.5)."""
    import urllib.request
    from veles.server import MasterServer
    from veles.client import SlaveClient
    from veles.web_status import WebStatus

    master_wf = make_wf("DashMasterWf", max_epochs=None)
    master_wf.decision.max_epochs = 2
    server = MasterServer(master_wf, "127.0.0.1:0", max_epochs=2)
    status = WebStatus(port=0)
    try:
        # what Launcher._run_master registers
        status.register("cluster", server.status)
        server.start_background()
        addr = "127.0.0.1:%d" % server.bound_address[1]
        slave_wf = make_wf("DashSlaveWf")
        slave_wf.is_slave = True
        seen = {}

        def run_slave():
            client = SlaveClient(slave_wf, addr, name="dash-slave")
            client.run_forever()

        t = threading.Thread(target=run_slave)
        t.start()
        # poll the dashboard WHILE the run is live
        deadline = time.time() + 60
        while time.time() < deadline:
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/status.json" % status.port,
                    timeout=5) as resp:
                seen = json.loads(resp.read().decode())["cluster"]
            if seen.get("n_slaves", 0) >= 1 and any(
                    s.get("jobs", 0) > 0
                    for s in seen.get("slaves", {}).values()):
                break
            time.sleep(0.05)
        t.join(timeout=120)
        assert seen.get("n_slaves", 0) >= 1, seen
        assert any(s.get("name") == "dash-slave"
                   for s in seen["slaves"].values()), seen
        assert any(s.get("jobs", 0) > 0
                   for s in seen["slaves"].values()), seen
        # page renders too (no provider crash)
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/" % status.port, timeout=5) as r:
            assert b"cluster" in r.read()
    finally:
        status.close()
        server.done.set()
